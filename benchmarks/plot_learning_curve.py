"""Extract a learning curve from a training run's stdout log.

The training loops print ``Rank-0: policy_step=N, reward_env_i=R`` on every
episode end; this tool bins those into a curve and writes a compact JSON
artifact (plus an ASCII sparkline for quick reading).

Usage: python benchmarks/plot_learning_curve.py <log> [out.json] [bin=4000]
"""

import json
import re
import sys
from collections import defaultdict

_LINE = re.compile(r"policy_step=(\d+), reward_env_\d+=([-+\d.eE]+)")


def extract(log_path: str, bin_size: int = 4000):
    bins = defaultdict(list)
    for line in open(log_path, errors="ignore"):
        m = _LINE.search(line)
        if m:
            step, rew = int(m.group(1)), float(m.group(2))
            bins[(step // bin_size) * bin_size].append(rew)
    return [
        {"policy_step": k, "reward_mean": sum(v) / len(v), "reward_max": max(v), "episodes": len(v)}
        for k, v in sorted(bins.items())
    ]


def sparkline(curve, width: int = 60) -> str:
    if not curve:
        return "(empty)"
    blocks = "▁▂▃▄▅▆▇█"
    vals = [c["reward_mean"] for c in curve]
    lo, hi = min(vals), max(vals)
    rng = (hi - lo) or 1.0
    return "".join(blocks[int((v - lo) / rng * (len(blocks) - 1))] for v in vals[:width])


if __name__ == "__main__":
    log = sys.argv[1]
    out = sys.argv[2] if len(sys.argv) > 2 else None
    bin_size = int(sys.argv[3]) if len(sys.argv) > 3 else 4000
    curve = extract(log, bin_size)
    for c in curve:
        print(f"step {c['policy_step']:>8,}  mean {c['reward_mean']:7.1f}  max {c['reward_max']:7.1f}  ({c['episodes']} eps)")
    print(sparkline(curve))
    if out:
        json.dump(curve, open(out, "w"), indent=1)
        print(f"-> {out}")
