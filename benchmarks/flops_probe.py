"""Per-device compiled-FLOPs probe for the six Dreamer-family train fns.

Correctness tests CANNOT catch silent replication: a sharded program that
GSPMD decides to all-gather-and-replicate still computes the right answer,
just N times over (round 3 shipped exactly that bug in PPO's epoch shuffle
and the Dreamers' imagination flatten).  What does catch it is XLA's own
cost analysis of the compiled per-device program: with the global batch
fixed, an honestly sharded step's per-device FLOPs must drop ~1/N with
mesh size N, while a silently replicated one stays ~1.0.

This probe lowers + compiles each Dreamer-family train fn (DV1, DV2, DV3,
P2E-DV1/DV2/DV3 exploration) at mesh sizes 1 and 8 on the virtual CPU
platform and records flops(8)/flops(1) per device.  Nothing is executed —
only compiled — so it runs anywhere in ~minutes.  A trimmed version gates
CI in tests/test_parallel/test_flops_probe.py.

Usage:  python benchmarks/flops_probe.py [--out benchmarks/results/scaling_r4_flops.json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")

import gymnasium as gym
import jax.numpy as jnp
import numpy as np

# tiny-but-structurally-faithful sizes: scans, heads, ensembles and both
# optimizers all present; compile time stays CI-friendly
_COMMON = [
    "env=dummy",
    "env.num_envs=1",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[]",
    "algo.per_rank_batch_size=64",
    "algo.per_rank_sequence_length=8",
    "algo.horizon=4",
    "algo.dense_units=64",
    "algo.mlp_layers=1",
    "algo.world_model.encoder.cnn_channels_multiplier=4",
]
_RSSM_SMALL = [
    "algo.world_model.recurrent_model.recurrent_state_size=64",
    "algo.world_model.representation_model.hidden_size=64",
    "algo.world_model.transition_model.hidden_size=64",
]
T, B = 8, 64
ACTIONS_DIM = (6,)


def _data(is_first: bool):
    rng = np.random.default_rng(0)
    d = {
        "rgb": jnp.asarray(rng.integers(0, 255, size=(T, B, 64, 64, 3)).astype(np.float32)),
        "actions": jnp.asarray(rng.normal(size=(T, B, 6)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(T, B, 1)).astype(np.float32)),
        "terminated": jnp.zeros((T, B, 1), jnp.float32),
        "truncated": jnp.zeros((T, B, 1), jnp.float32),
    }
    if is_first:
        d["is_first"] = jnp.zeros((T, B, 1), jnp.float32)
    return d


def _runtime(devices: int):
    from sheeprl_tpu.parallel.mesh import MeshRuntime

    rt = MeshRuntime(devices=devices, accelerator="cpu").launch()
    rt.seed_everything(0)
    return rt


def _obs_space():
    return gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (64, 64, 3), np.uint8)})


def _compiled_flops(runtime, train_fn, args):
    from sheeprl_tpu.obs import compiled_flops
    from sheeprl_tpu.utils.jax_compat import set_mesh

    with set_mesh(runtime.mesh):
        compiled = train_fn._jitted.lower(*args).compile()
    return compiled_flops(compiled) or 0.0


def probe_dv(version: int, devices: int) -> float:
    """DV1/DV2/DV3 (version in {1,2,3}) per-device compiled flops."""
    mod = __import__(f"sheeprl_tpu.algos.dreamer_v{version}.dreamer_v{version}", fromlist=["x"])
    agent_mod = __import__(f"sheeprl_tpu.algos.dreamer_v{version}.agent", fromlist=["x"])
    from sheeprl_tpu.config import compose

    cfg = compose(overrides=[f"exp=dreamer_v{version}"] + _COMMON + _RSSM_SMALL)
    runtime = _runtime(devices)
    world_model, actor, critic, params = agent_mod.build_agent(
        runtime, ACTIONS_DIM, True, cfg, _obs_space()
    )
    params = runtime.replicate(params)
    txs = tuple(
        mod._make_optimizer(getattr(cfg.algo, k).optimizer, getattr(cfg.algo, k).clip_gradients)
        for k in ("world_model", "actor", "critic")
    )
    opt_states = runtime.replicate(
        {k: tx.init(params[k]) for k, tx in zip(("world_model", "actor", "critic"), txs)}
    )
    train_fn = mod.make_train_fn(
        runtime, world_model, actor, critic, txs, cfg, True, ACTIONS_DIM
    )
    data = runtime.shard_batch(_data(is_first=version >= 2), axis=1)
    if version == 3:
        from sheeprl_tpu.algos.dreamer_v3.utils import init_moments

        moments = runtime.replicate(init_moments())
        args = (params, opt_states, moments, data, runtime.next_key())
    else:
        args = (params, opt_states, data, runtime.next_key())
    return _compiled_flops(runtime, train_fn, args)


def probe_p2e(version: int, devices: int) -> float:
    """P2E-DV1/DV2/DV3 exploration per-device compiled flops."""
    mod = __import__(
        f"sheeprl_tpu.algos.p2e_dv{version}.p2e_dv{version}_exploration", fromlist=["x"]
    )
    agent_mod = __import__(f"sheeprl_tpu.algos.p2e_dv{version}.agent", fromlist=["x"])
    from sheeprl_tpu.config import compose

    cfg = compose(overrides=[f"exp=p2e_dv{version}_exploration"] + _COMMON + _RSSM_SMALL)
    runtime = _runtime(devices)
    if version == 3:
        world_model, actor, critic, ensemble, critics_cfg, params = agent_mod.build_agent(
            runtime, ACTIONS_DIM, True, cfg, _obs_space()
        )
        params = runtime.replicate(params)
        mk = mod._make_optimizer
        wm_tx = mk(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
        ens_tx = mk(cfg.algo.ensembles.optimizer, cfg.algo.ensembles.clip_gradients)
        a_t = mk(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
        c_t = mk(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
        a_e = mk(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
        c_es = {
            name: mk(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
            for name in critics_cfg
        }
        opt_states = runtime.replicate(
            {
                "world_model": wm_tx.init(params["world_model"]),
                "ensembles": ens_tx.init(params["ensembles"]),
                "actor_task": a_t.init(params["actor_task"]),
                "critic_task": c_t.init(params["critic_task"]),
                "actor_exploration": a_e.init(params["actor_exploration"]),
                "critics_exploration": {
                    name: c_es[name].init(params["critics_exploration"][name]["module"])
                    for name in critics_cfg
                },
            }
        )
        train_fn = mod.make_train_fn(
            runtime, world_model, actor, critic, ensemble, critics_cfg,
            (wm_tx, ens_tx, a_t, c_t, a_e, c_es), cfg, True, ACTIONS_DIM,
        )
        from sheeprl_tpu.algos.dreamer_v3.utils import init_moments

        moments_task = runtime.replicate(init_moments())
        moments_expl = runtime.replicate({name: init_moments() for name in critics_cfg})
        data = runtime.shard_batch(_data(is_first=True), axis=1)
        args = (params, opt_states, moments_task, moments_expl, data, runtime.next_key())
    else:
        world_model, actor, critic, ensemble, params = agent_mod.build_agent(
            runtime, ACTIONS_DIM, True, cfg, _obs_space()
        )
        params = runtime.replicate(params)
        mk = mod._make_optimizer
        wm_tx = mk(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
        ens_tx = mk(cfg.algo.ensembles.optimizer, cfg.algo.ensembles.clip_gradients)
        a_t = mk(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
        c_t = mk(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
        a_e = mk(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
        c_e = mk(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
        opt_states = runtime.replicate(
            {
                "world_model": wm_tx.init(params["world_model"]),
                "ensembles": ens_tx.init(params["ensembles"]),
                "actor_task": a_t.init(params["actor_task"]),
                "critic_task": c_t.init(params["critic_task"]),
                "actor_exploration": a_e.init(params["actor_exploration"]),
                "critic_exploration": c_e.init(params["critic_exploration"]),
            }
        )
        train_fn = mod.make_train_fn(
            runtime, world_model, actor, critic, ensemble,
            (wm_tx, ens_tx, a_t, c_t, a_e, c_e), cfg, True, ACTIONS_DIM,
        )
        data = runtime.shard_batch(_data(is_first=version >= 2), axis=1)
        args = (params, opt_states, data, runtime.next_key())
    return _compiled_flops(runtime, train_fn, args)


PROBES = {
    "dreamer_v1": lambda d: probe_dv(1, d),
    "dreamer_v2": lambda d: probe_dv(2, d),
    "dreamer_v3": lambda d: probe_dv(3, d),
    "p2e_dv1": lambda d: probe_p2e(1, d),
    "p2e_dv2": lambda d: probe_p2e(2, d),
    "p2e_dv3": lambda d: probe_p2e(3, d),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="benchmarks/results/scaling_r4_flops.json")
    ap.add_argument("--algos", default=",".join(PROBES))
    args = ap.parse_args()
    rows = {}
    for name in args.algos.split(","):
        f1 = PROBES[name](1)
        f8 = PROBES[name](8)
        ratio = f8 / f1 if f1 else float("nan")
        rows[name] = {
            "flops_per_device_1dev": f1,
            "flops_per_device_8dev": f8,
            "ratio_8dev_over_1dev": round(ratio, 4),
            # 1/8 = 0.125 is ideal; collectives and unshardable tails push it
            # up a little; ~1.0 means silent replication
            "verdict": "sharded" if ratio < 0.3 else ("PARTIAL" if ratio < 0.7 else "REPLICATED"),
        }
        print(json.dumps({"algo": name, **rows[name]}))
    out = {
        "protocol": (
            "XLA cost-analysis flops of the compiled per-device train program at mesh "
            "sizes 1 vs 8 (virtual CPU devices), global batch fixed at "
            f"B={B} x T={T}; nothing executed. Ideal ratio 0.125."
        ),
        "algos": rows,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
