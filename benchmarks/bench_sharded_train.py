"""Sharded-train ladder: PPO + compact DreamerV3 update step time at
1/2/4/8 mesh devices, DP and FSDP legs, on the virtual host-platform mesh.

All "devices" share ONE physical core, so wall-clock cannot improve with
mesh size; with the global batch fixed (strong scaling) the IDEAL sharded
program keeps normalized step time at ~1.0 at every mesh size — the
ladder measures the partitioning/collective overhead of the 2-D
("data", "fsdp") mesh path (parallel/sharding.py), which is exactly the
term that would also tax a real pod.  ``achieved_vs_ideal`` is
t(1 device) / t(N devices) with ideal 1.0 on this box (N on a real pod).

One leg per algo additionally records the ``Compiled.cost_analysis()``
collective-bytes estimate (the telemetry ``mesh`` key's opt-in field) —
the cross-device traffic the compiled update would move per dispatch.

Writes benchmarks/results/sharded_train_r12.json; wired as bench.py's
``mesh`` section under the PR-6 perf gate.

Usage: python benchmarks/bench_sharded_train.py [--steps N] [--out PATH]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

LADDER = (1, 2, 4, 8)
FSDP_LADDER = (2, 8)  # fsdp == dp at 1 device; 2/8 bracket the overhead


def _time_step(step, carry, n_warm=2, n_steps=6):
    for _ in range(n_warm):
        carry = step(carry)
        jax.block_until_ready(carry)
    tic = time.perf_counter()
    for _ in range(n_steps):
        carry = step(carry)
    jax.block_until_ready(carry)
    return (time.perf_counter() - tic) / n_steps


def bench_ppo(devices: int, strategy: str, steps: int, want_cost: bool = False):
    """Full PPO update on a `devices`-wide mesh (shard_map DDP core under
    dp, GSPMD + layout constraints under fsdp); global rollout fixed at
    T=64 x 32 envs."""
    import gymnasium as gym

    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import build_ppo_optimizer, make_update_fn
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.parallel.mesh import MeshRuntime
    from sheeprl_tpu.parallel.sharding import collective_bytes_estimate

    cfg = compose(
        overrides=[
            "exp=ppo",
            "env=dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            "env.num_envs=32",
            "algo.rollout_steps=64",
            "algo.per_rank_batch_size=128",
            "algo.update_epochs=2",
        ]
    )
    runtime = MeshRuntime(devices=devices, strategy=strategy, accelerator="cpu").launch()
    runtime.seed_everything(0)
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-1, 1, (64,), np.float32)})
    module, params = build_agent(runtime, (4,), False, cfg, obs_space)
    params = runtime.replicate(params)
    tx = build_ppo_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm, runtime.precision)
    opt_state = runtime.replicate(tx.init(params))
    update_fn = make_update_fn(runtime, module, tx, cfg, ["state"])

    T, E = 64, 32
    rng = np.random.default_rng(0)
    data = {
        "state": jnp.asarray(rng.normal(size=(T, E, 64)).astype(np.float32)),
        "values": jnp.asarray(rng.normal(size=(T, E, 1)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(T, E, 1)).astype(np.float32)),
        "dones": jnp.zeros((T, E, 1), jnp.float32),
        "logprobs": jnp.asarray(rng.normal(size=(T, E, 1)).astype(np.float32)),
        "actions": jnp.asarray(rng.integers(0, 4, size=(T, E, 1)).astype(np.float32)),
    }
    data = runtime.shard_batch(data, axis=1)
    next_obs = runtime.shard_batch(
        {"state": jnp.asarray(rng.normal(size=(E, 64)).astype(np.float32))}, axis=0
    )
    args = (params, opt_state, data, next_obs, runtime.next_key(),
            jnp.float32(0.2), jnp.float32(0.0), jnp.float32(3e-4))
    cost = None
    if want_cost and update_fn._jitted is not None:
        cost = collective_bytes_estimate(update_fn._jitted.lower(*args).compile())

    def step(carry):
        params, opt_state = carry
        params, opt_state, _ = update_fn(
            params, opt_state, data, next_obs, runtime.next_key(),
            jnp.float32(0.2), jnp.float32(0.0), jnp.float32(3e-4),
        )
        return params, opt_state

    dt = _time_step(step, (params, opt_state), n_steps=steps)
    return dt, T * E, cost


def bench_dv3(devices: int, strategy: str, steps: int, want_cost: bool = False):
    """Compact DreamerV3 train step (wm + imagination + actor + critic) on
    a `devices`-wide mesh; global batch fixed at B=16 x T=8 pixels."""
    import gymnasium as gym

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import _make_optimizer, make_train_fn
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.parallel.mesh import MeshRuntime
    from sheeprl_tpu.parallel.sharding import collective_bytes_estimate

    cfg = compose(
        overrides=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.num_envs=1",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.per_rank_batch_size=16",
            "algo.per_rank_sequence_length=8",
            "algo.horizon=4",
            "algo.world_model.recurrent_model.recurrent_state_size=128",
            "algo.world_model.representation_model.hidden_size=128",
            "algo.world_model.transition_model.hidden_size=128",
            "algo.world_model.encoder.cnn_channels_multiplier=4",
            "algo.dense_units=128",
            "algo.mlp_layers=1",
        ]
    )
    runtime = MeshRuntime(devices=devices, strategy=strategy, accelerator="cpu").launch()
    runtime.seed_everything(0)
    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (64, 64, 3), np.uint8)})
    world_model, actor, critic, params = build_agent(runtime, (6,), True, cfg, obs_space)
    params = runtime.replicate(params)
    wm_tx = _make_optimizer(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
    actor_tx = _make_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_tx = _make_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    opt_states = runtime.replicate(
        {
            "world_model": wm_tx.init(params["world_model"]),
            "actor": actor_tx.init(params["actor"]),
            "critic": critic_tx.init(params["critic"]),
        }
    )
    moments = runtime.replicate(init_moments())
    train_fn = make_train_fn(
        runtime, world_model, actor, critic, (wm_tx, actor_tx, critic_tx), cfg, True, (6,)
    )
    T, B = 8, 16
    rng = np.random.default_rng(0)
    data = {
        "rgb": jnp.asarray(rng.integers(0, 255, size=(T, B, 64, 64, 3), dtype=np.uint8)),
        "actions": jnp.asarray(rng.normal(size=(T, B, 6)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(T, B, 1)).astype(np.float32)),
        "terminated": jnp.zeros((T, B, 1), jnp.float32),
        "truncated": jnp.zeros((T, B, 1), jnp.float32),
        "is_first": jnp.zeros((T, B, 1), jnp.float32),
    }
    data = runtime.shard_batch(data, axis=1)
    cost = None
    if want_cost and train_fn._jitted is not None:
        cost = collective_bytes_estimate(
            train_fn._jitted.lower(params, opt_states, moments, data, runtime.next_key()).compile()
        )

    def step(carry):
        params, opt_states, moments = carry
        params, opt_states, moments, _ = train_fn(
            params, opt_states, moments, data, runtime.next_key()
        )
        return params, opt_states, moments

    dt = _time_step(step, (params, opt_states, moments), n_steps=steps)
    return dt, T * B, cost


def run_ladder(steps: int):
    rows = []
    base = {}
    for algo, fn in (("ppo", bench_ppo), ("dv3", bench_dv3)):
        legs = [("dp", d) for d in LADDER] + [("fsdp", d) for d in FSDP_LADDER]
        for strategy, d in legs:
            want_cost = strategy == "dp" and d == 8
            dt, frames, cost = fn(d, strategy, steps, want_cost=want_cost)
            key = (algo, strategy, d)
            if strategy == "dp" and d == 1:
                base[algo] = dt
            row = {
                "algo": algo,
                "strategy": strategy,
                "devices": d,
                "step_ms": round(dt * 1e3, 2),
                "frames_per_s": round(frames / dt, 1),
                # strong scaling on a shared core: ideal == 1.0 (see module
                # docstring); on a real pod ideal == devices
                "achieved_vs_ideal": round(base[algo] / dt, 3) if algo in base else None,
            }
            if cost is not None:
                row["collective_bytes_estimate"] = cost
            rows.append(row)
            print(json.dumps(row))
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(__file__), "results", "sharded_train_r12.json"),
    )
    args = ap.parse_args()
    if len(jax.devices()) < max(LADDER):
        raise RuntimeError(
            f"need {max(LADDER)} host devices; set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={max(LADDER)}"
        )
    rows = run_ladder(args.steps)
    out = {
        "metric": "sharded_train_ladder",
        "legs": rows,
        "host_cpu_count": os.cpu_count(),
        "note": (
            "virtual host-platform mesh on a shared core: normalized strong-"
            "scaling ladder (ideal achieved_vs_ideal == 1.0 here, == N on a pod); "
            "fsdp legs run the GSPMD+layout-constraint ZeRO program"
        ),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"metric": "sharded_train_written", "out": args.out}))


if __name__ == "__main__":
    main()
