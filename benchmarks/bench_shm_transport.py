"""Rollout-transport ladder: pickled mp.Queue vs SharedMemory ring vs tcp.

Round-trips a synthetic rollout payload parent->child->ack across a
spawned process at several payload sizes and reports µs/message for all
three ``algo.decoupled_transport`` backends plus their speedups over the
pickled queue.  This isolates exactly what the transport setting changes
— the per-iteration shipping cost — from everything else the decoupled
topology does (env stepping, train dispatch, scheduling), so the numbers
hold on any host, including 1-core containers where end-to-end
decoupled-vs-coupled is core-bound.  The tcp leg runs over localhost
loopback; across real hosts it pays the NIC instead, which is the point
of having it on the ladder.

    python benchmarks/bench_shm_transport.py [--out results/transport_ladder.json]
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sheeprl_tpu.parallel.shm_ring import ShmReceiver, ShmSender  # noqa: E402
from sheeprl_tpu.parallel.transport import TcpChannel, TcpListener, make_transport  # noqa: E402

MODES = ("queue", "shm", "tcp")


def _payload(nbytes: int):
    """Rollout-shaped payload: one big obs block + small per-step arrays."""
    rows = max(nbytes // (4 * 68), 1)
    rng = np.random.default_rng(0)
    return [
        ("obs", rng.normal(size=(rows, 64)).astype(np.float32)),
        ("actions", rng.integers(0, 3, size=(rows, 2)).astype(np.float32)),
        ("rewards", rng.normal(size=(rows, 1)).astype(np.float32)),
        ("dones", rng.integers(0, 2, size=(rows, 1)).astype(np.uint8)),
    ]


def _consumer(mode, data_q, ack_q, free_q, address, n_msgs):
    if mode == "tcp":
        chan = TcpChannel(address=tuple(address), player_id=0, window=2)
        try:
            for _ in range(n_msgs):
                frame = chan.recv(timeout=60)
                s = float(frame.arrays["rewards"][0, 0])  # touch the data
                frame.release()
                ack_q.put(s)
        finally:
            chan.close()
        return
    rx = ShmReceiver(free_q)
    try:
        for _ in range(n_msgs):
            msg = data_q.get()
            if msg[0] == "shm":
                _, info, slot, leaves = msg
                views = rx.unpack(info, slot, leaves, copy=False)
                s = float(views["rewards"][0, 0])  # touch the data
                del views
                rx.release(slot)
            else:
                _, payload = msg
                s = float(payload["rewards"][0, 0])
            ack_q.put(s)
    finally:
        rx.close()


def _run_mode(mode: str, payload, n_msgs: int) -> float:
    """Seconds per message for one transport mode."""
    ctx = mp.get_context("spawn")
    data_q, ack_q, free_q = ctx.Queue(), ctx.Queue(), ctx.Queue()
    listener = TcpListener("127.0.0.1", 0, window=2) if mode == "tcp" else None
    address = list(listener.address) if listener else None
    proc = ctx.Process(target=_consumer, args=(mode, data_q, ack_q, free_q, address, n_msgs))
    proc.start()
    tx = ShmSender(free_q, min_bytes=0) if mode == "shm" else None
    chan = listener.channel(0, timeout=60, peer_alive=proc.is_alive) if listener else None
    try:
        # warm both directions (spawn + first-attach costs stay out of the rate)
        t0 = None
        for i in range(n_msgs):
            if i == n_msgs // 10 + 1:
                t0 = time.perf_counter()
                sent_at = i
            if mode == "shm":
                sent = tx.send(
                    data_q.put, "shm", payload, (), acquire_slot=lambda: free_q.get(timeout=30)
                )
                assert sent
            elif mode == "tcp":
                chan.send("shm", arrays=payload, seq=i, timeout=60)
            else:
                data_q.put(("pickle", {k: v for k, v in payload}))
            ack_q.get(timeout=30)
        elapsed = time.perf_counter() - t0
        return elapsed / (n_msgs - sent_at)
    finally:
        if tx is not None:
            tx.close()
        if chan is not None:
            chan.close()
        if listener is not None:
            listener.close()
        proc.join(timeout=30)
        if proc.is_alive():
            proc.terminate()


# ----------------------------------------------------- crc-overhead legs
def _chan_consumer(spec, ack_q, n_msgs, flight_dir=None):
    if flight_dir:
        # receive-side recorder: the tracing leg must pay BOTH halves of
        # the cost (marker strip + recv record), like a real player does
        from sheeprl_tpu.obs import flight

        flight.configure("bench_rx", flight_dir, mode="sampled")
    chan = spec.player_channel()
    try:
        for _ in range(n_msgs):
            frame = chan.recv(timeout=60)
            s = float(frame.arrays["rewards"][0, 0])  # touch the data
            frame.release()
            del frame  # drop the shm views before the arena teardown
            ack_q.put(s)
    finally:
        chan.close()


def _run_channel_mode(
    backend: str,
    payload,
    n_msgs: int,
    integrity: str,
    tracing: str = "off",
    flight_dir=None,
    wire_format: str = "v1",
) -> float:
    """Seconds/message through the REAL Channel API (hub -> player
    direction), identical code paths apart from ``integrity``/``tracing``/
    ``wire_format`` — so the paired delta measures exactly what the
    toggled layer adds (or, for the wire codec, saves) and nothing else."""
    ctx = mp.get_context("spawn")
    if tracing != "off":
        from sheeprl_tpu.obs import flight

        flight.configure("bench_tx", flight_dir, mode=tracing)
    hub, specs = make_transport(
        ctx, backend, 1, min_bytes=0, integrity=integrity, tracing=tracing, wire_format=wire_format
    )
    ack_q = ctx.Queue()
    proc = ctx.Process(
        target=_chan_consumer,
        args=(specs[0], ack_q, n_msgs, flight_dir if tracing != "off" else None),
    )
    proc.start()
    try:
        chan = hub.channel(0, timeout=60, peer_alive=proc.is_alive)
        t0 = None
        sent_at = 0
        for i in range(n_msgs):
            if i == n_msgs // 10 + 1:
                t0 = time.perf_counter()
                sent_at = i
            chan.send("data", arrays=payload, seq=i, timeout=60)
            ack_q.get(timeout=60)
        return (time.perf_counter() - t0) / (n_msgs - sent_at)
    finally:
        hub.close()
        proc.join(timeout=30)
        if proc.is_alive():
            proc.terminate()
        if tracing != "off":
            from sheeprl_tpu.obs import flight

            flight.close_recorder()


def run_integrity_ladder(n_msgs: int = 150, sizes_mb=(0.25, 1), repeats: int = 3):
    """Paired off-vs-crc legs (ISSUE 10 acceptance: crc overhead < 5%
    on the 1 MB shm/tcp legs).  Returns one row per payload size.

    Single runs of the round-trip rate swing 20-30% on a shared host
    (scheduler noise dwarfs the checksum), so each mode runs ``repeats``
    times INTERLEAVED and the minimum — the least-perturbed estimate of
    the true cost — feeds the overhead ratio."""
    from sheeprl_tpu.resilience.integrity import CHECKSUM_IMPL, default_coverage

    rows = []
    for size_mb in sizes_mb:
        payload = _payload(int(size_mb * (1 << 20)))
        actual = sum(int(a.nbytes) for _, a in payload)
        n = max(min(n_msgs, int(64e6 / max(actual, 1))), 30)
        row = {
            "payload_mb": round(actual / (1 << 20), 3),
            "msgs": n,
            "repeats": repeats,
            "checksum_impl": CHECKSUM_IMPL,
            "coverage_bytes": default_coverage(),
        }
        for backend in ("shm", "tcp"):
            best = {"off": float("inf"), "crc": float("inf")}
            for _ in range(repeats):
                for mode in ("off", "crc"):
                    best[mode] = min(best[mode], _run_channel_mode(backend, payload, n, mode))
            row[f"{backend}_off_us_per_msg"] = round(best["off"] * 1e6, 1)
            row[f"{backend}_crc_us_per_msg"] = round(best["crc"] * 1e6, 1)
            row[f"{backend}_crc_overhead_pct"] = round(
                (best["crc"] / best["off"] - 1.0) * 100, 2
            )
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


def _tree_payload(nbytes: int, n_leaves: int):
    """Pytree-shaped payload: (matrix, bias) pairs like a params
    broadcast or a dict-obs rollout shard.  Leaf COUNT is the axis the
    wire format changes — v1 pays one pickle entry + one ``sendall``
    per leaf, v2 a cached table row + one slot in a single gather write
    — so the ladder must ship realistic trees, not four flat blocks."""
    rng = np.random.default_rng(0)
    pairs = max(n_leaves // 2, 1)
    per = max(nbytes // (4 * pairs), 64)
    payload = []
    for i in range(pairs):
        payload.append((f"p/{i:03d}/w", rng.normal(size=(per // 64, 64)).astype(np.float32)))
        payload.append((f"p/{i:03d}/b", rng.normal(size=(64,)).astype(np.float32)))
    return payload


def _stream_consumer(spec, ack_q, n_msgs):
    chan = spec.player_channel()
    try:
        for _ in range(n_msgs):
            frame = chan.recv(timeout=60)
            frame.release()
            del frame  # drop the views before the arena teardown
        ack_q.put(n_msgs)
    finally:
        chan.close()


def _run_channel_stream(
    backend: str, payload, n_msgs: int, wire_format: str, window: int = 6
) -> float:
    """Seconds/message at STREAMING rate: the sender keeps ``window``
    frames in flight (the credit gate is the only brake) and the clock
    stops when the consumer acks the last frame.  This is the honest
    protocol for a transport whose job is overlapped rollout shipping —
    a per-message ping-pong ack would serialize both codecs behind the
    same context-switch floor and measure the scheduler, not the wire."""
    ctx = mp.get_context("spawn")
    hub, specs = make_transport(
        ctx, backend, 1, min_bytes=0, window=window, wire_format=wire_format
    )
    ack_q = ctx.Queue()
    proc = ctx.Process(target=_stream_consumer, args=(specs[0], ack_q, n_msgs))
    proc.start()
    try:
        chan = hub.channel(0, timeout=60, peer_alive=proc.is_alive)
        warm = n_msgs // 10 + 1
        t0 = 0.0
        for i in range(n_msgs):
            if i == warm:
                t0 = time.perf_counter()
            chan.send("data", arrays=payload, seq=i, timeout=60)
        ack_q.get(timeout=120)
        return (time.perf_counter() - t0) / (n_msgs - warm)
    finally:
        hub.close()
        proc.join(timeout=30)
        if proc.is_alive():
            proc.terminate()


# (payload_mb, leaves) rungs: a small rollout shard, a dict-obs shard,
# and a params-tree-sized broadcast — the 1 MB tcp rung is the ISSUE-19
# acceptance headline
WIRE_RUNGS = ((0.0625, 8), (0.25, 16), (1, 32))


def run_wire_ladder(n_msgs: int = 150, rungs=WIRE_RUNGS, repeats: int = 3, backends=("tcp", "shm")):
    """Paired v1-vs-v2 wire-format legs (ISSUE 19 acceptance: v2 holds
    >= 1.5x on the 1 MB tcp rung).  Interleaved min-of-N, like
    :func:`run_integrity_ladder` — the two codecs alternate within each
    repeat so scheduler noise perturbs both sides equally, and the
    per-mode minimum feeds the speedup ratio; each leg runs the
    streaming protocol (:func:`_run_channel_stream`)."""
    rows = []
    for size_mb, n_leaves in rungs:
        payload = _tree_payload(int(size_mb * (1 << 20)), n_leaves)
        actual = sum(int(a.nbytes) for _, a in payload)
        n = max(min(n_msgs, int(64e6 / max(actual, 1))), 30)
        row = {
            "payload_mb": round(actual / (1 << 20), 3),
            "leaves": len(payload),
            "msgs": n,
            "repeats": repeats,
        }
        for backend in backends:
            best = {"v1": float("inf"), "v2": float("inf")}
            for _ in range(repeats):
                for wf in ("v1", "v2"):
                    best[wf] = min(best[wf], _run_channel_stream(backend, payload, n, wf))
            row[f"{backend}_v1_us_per_msg"] = round(best["v1"] * 1e6, 1)
            row[f"{backend}_v2_us_per_msg"] = round(best["v2"] * 1e6, 1)
            row[f"{backend}_v2_speedup_x"] = round(best["v1"] / best["v2"], 3)
        rows.append(row)
        print(json.dumps(row), flush=True)
    return rows


def run_tracing_ladder(n_msgs: int = 150, sizes_mb=(0.25, 1), repeats: int = 3, flight_dir=None):
    """Paired off-vs-sampled flight-tracing legs (ISSUE 13 acceptance:
    sampled tracing holds <2% on the 1 MB shm rung).  Same interleaved
    min-of-N protocol as :func:`run_integrity_ladder` — single runs swing
    20-30% on a shared host.  With ``flight_dir`` set, both endpoints
    record real flight streams there (the honest cost: marker append +
    two records per message + chunked JSONL writes), and the caller can
    run ``obs.report`` over it to export a trace.json."""
    import shutil
    import tempfile

    own_dir = flight_dir is None
    flight_dir = flight_dir or tempfile.mkdtemp(prefix="sheeprl_bench_flight_")
    rows = []
    try:
        for size_mb in sizes_mb:
            payload = _payload(int(size_mb * (1 << 20)))
            actual = sum(int(a.nbytes) for _, a in payload)
            n = max(min(n_msgs, int(64e6 / max(actual, 1))), 30)
            row = {"payload_mb": round(actual / (1 << 20), 3), "msgs": n, "repeats": repeats}
            for backend in ("shm",):
                best = {"off": float("inf"), "on": float("inf")}
                for _ in range(repeats):
                    best["off"] = min(
                        best["off"], _run_channel_mode(backend, payload, n, "off")
                    )
                    best["on"] = min(
                        best["on"],
                        _run_channel_mode(
                            backend, payload, n, "off", tracing="sampled",
                            flight_dir=os.path.join(flight_dir, "flight"),
                        ),
                    )
                row[f"{backend}_off_us_per_msg"] = round(best["off"] * 1e6, 1)
                row[f"{backend}_tracing_us_per_msg"] = round(best["on"] * 1e6, 1)
                row[f"{backend}_tracing_overhead_pct"] = round(
                    (best["on"] / best["off"] - 1.0) * 100, 2
                )
            rows.append(row)
            print(json.dumps(row), flush=True)
    finally:
        if own_dir:
            shutil.rmtree(flight_dir, ignore_errors=True)
    return rows


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--msgs", type=int, default=200)
    ap.add_argument(
        "--integrity",
        action="store_true",
        help="also run the paired off-vs-crc Channel-API legs (ISSUE 10)",
    )
    ap.add_argument(
        "--tracing",
        action="store_true",
        help="also run the paired off-vs-sampled flight-tracing legs (ISSUE 13)",
    )
    args = ap.parse_args()

    results = {"host_cpu_count": os.cpu_count(), "sizes": []}
    for size_mb in (0.015, 0.25, 1, 4, 16):
        nbytes = int(size_mb * (1 << 20))
        payload = _payload(nbytes)
        actual = sum(int(a.nbytes) for _, a in payload)
        n = max(min(args.msgs, int(64e6 / max(actual, 1))), 20)
        t_q = _run_mode("queue", payload, n)
        t_s = _run_mode("shm", payload, n)
        t_t = _run_mode("tcp", payload, n)
        row = {
            "payload_mb": round(actual / (1 << 20), 3),
            "queue_us_per_msg": round(t_q * 1e6, 1),
            "shm_us_per_msg": round(t_s * 1e6, 1),
            "tcp_us_per_msg": round(t_t * 1e6, 1),
            "shm_speedup": round(t_q / t_s, 3),
            "tcp_over_queue": round(t_q / t_t, 3),
            "msgs": n,
        }
        results["sizes"].append(row)
        print(json.dumps(row), flush=True)

    if args.integrity:
        results["integrity"] = run_integrity_ladder(n_msgs=args.msgs)

    if args.tracing:
        results["tracing"] = run_tracing_ladder(n_msgs=args.msgs)

    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
