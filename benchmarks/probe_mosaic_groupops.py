"""Probe: which in-kernel group-softmax/argmax designs can Mosaic lower?

The fused sequence-RSSM kernel must sample a (B, 32 groups, 32 classes)
one-hot categorical INSIDE the sequential kernel (unimix softmax per group,
argmax of mixed logits + gumbel, one-hot), while the matmul chain wants the
flat (B, 1024) layout.  Two candidate designs:

* ``reshape``  — (B, 1024) -> (B, 32, 32) in-kernel reshape + softmax/argmax
  over the trailing 32.  REJECTED by Mosaic on v5e ("infer-vector-layout:
  unsupported shape cast", probed 2026-08-01); kept here as a canary for
  future toolchains.
* ``segmax``   — reshape-free: per-group max via a 5-round segmented tree of
  lane rolls (``pltpu.roll``), group-start extraction + broadcast-back via
  two 0/1 selection matmuls (exact in f32), group sums likewise, and the
  one-hot as an equality mask normalized by the (tie-count) group sum.
  softmax(log p_mix) == p_mix, so the straight-through probabilities come
  for free.

Runs both in a minimal pallas_call on the current default platform and
diffs against the pure-jax computation. Usage:
    python benchmarks/probe_mosaic_groupops.py [--cpu] [--variant segmax|reshape]
"""

import json
import sys
import functools

import jax

if "--cpu" in sys.argv:
    # the axon sitecustomize imports jax before env vars can take effect;
    # jax.config works as long as no backend is initialized yet (conftest.py)
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

GROUPS = 32
CLASSES = 32
N = GROUPS * CLASSES


def _kernel_reshape(logits_ref, noise_ref, out_ref, probs_ref, *, unimix: float):
    l3 = logits_ref[:].reshape(logits_ref.shape[0], GROUPS, CLASSES)
    p = jax.nn.softmax(l3, -1)
    p = (1.0 - unimix) * p + unimix / CLASSES
    mixed = jnp.log(p)
    n3 = noise_ref[:].reshape(noise_ref.shape[0], GROUPS, CLASSES)
    idx = jnp.argmax(mixed + n3, -1)
    hard = (idx[..., None] == jax.lax.broadcasted_iota(jnp.int32, (1, 1, CLASSES), 2)).astype(
        jnp.float32
    )
    out_ref[:] = hard.reshape(out_ref.shape)
    probs_ref[:] = jax.nn.softmax(mixed, -1).reshape(probs_ref.shape)


def _segmax(x):
    """Position i -> max over lanes [i, i+CLASSES-1] (window never crosses a
    group boundary AT group-start positions, which are the only ones read)."""
    n = x.shape[1]
    s = 1
    while s < CLASSES:
        # roll left by s (shift must be non-negative: left-by-s == right-by-(n-s))
        x = jnp.maximum(x, pltpu.roll(x, shift=n - s, axis=1))
        s *= 2
    return x


def _kernel_segmax(logits_ref, noise_ref, sel_ref, bcast_ref, out_ref, probs_ref, *, unimix: float):
    l = logits_ref[:]  # (B, N) f32
    sel = sel_ref[:]  # (N, GROUPS) 0/1: picks group-start lanes
    bcast = bcast_ref[:]  # (GROUPS, N) 0/1: broadcasts per-group scalars back
    gm = jnp.dot(_segmax(l), sel, preferred_element_type=jnp.float32)  # (B, GROUPS)
    gm = jnp.dot(gm, bcast, preferred_element_type=jnp.float32)  # (B, N), exact copies
    e = jnp.exp(l - gm)
    # group sums: e @ (ones per group) == e @ (bcast.T as 0/1 membership)
    gs = jnp.dot(e, bcast.T, preferred_element_type=jnp.float32)  # (B, GROUPS)
    gs = jnp.dot(gs, bcast, preferred_element_type=jnp.float32)  # (B, N)
    p = (1.0 - unimix) * (e / gs) + unimix / CLASSES
    mixed = jnp.log(p)
    m2 = mixed + noise_ref[:]
    gm2 = jnp.dot(_segmax(m2), sel, preferred_element_type=jnp.float32)
    gm2 = jnp.dot(gm2, bcast, preferred_element_type=jnp.float32)
    mask = (m2 == gm2).astype(jnp.float32)
    ties = jnp.dot(mask, bcast.T, preferred_element_type=jnp.float32)
    ties = jnp.dot(ties, bcast, preferred_element_type=jnp.float32)
    out_ref[:] = mask / ties
    # softmax(log p_mix) == p_mix (p_mix sums to 1 per group)
    probs_ref[:] = p


def selection_matrices():
    sel = np.zeros((N, GROUPS), np.float32)
    for g in range(GROUPS):
        sel[g * CLASSES, g] = 1.0
    bcast = np.zeros((GROUPS, N), np.float32)
    for g in range(GROUPS):
        bcast[g, g * CLASSES : (g + 1) * CLASSES] = 1.0
    return jnp.asarray(sel), jnp.asarray(bcast)


def main():
    B = 16
    variant = "segmax"
    for i, a in enumerate(sys.argv):
        if a == "--variant":
            variant = sys.argv[i + 1]
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(scale=2.0, size=(B, N)), jnp.float32)
    noise = jnp.asarray(rng.gumbel(size=(B, N)), jnp.float32)
    interpret = jax.default_backend() != "tpu"

    out_shape = (
        jax.ShapeDtypeStruct((B, N), jnp.float32),
        jax.ShapeDtypeStruct((B, N), jnp.float32),
    )
    if variant == "reshape":
        fn = pl.pallas_call(
            functools.partial(_kernel_reshape, unimix=0.01), out_shape=out_shape, interpret=interpret
        )
        args = (logits, noise)
    else:
        sel, bcast = selection_matrices()
        fn = pl.pallas_call(
            functools.partial(_kernel_segmax, unimix=0.01), out_shape=out_shape, interpret=interpret
        )
        args = (logits, noise, sel, bcast)

    try:
        hard, probs = jax.jit(fn)(*args)
        hard.block_until_ready()
    except Exception as e:  # noqa: BLE001 - report any lowering failure
        with open("/tmp/probe_mosaic_full_error.log", "w") as f:
            f.write(str(e))
        print(json.dumps({"ok": False, "variant": variant, "backend": jax.default_backend(), "error": str(e)[:500]}))
        sys.exit(1)

    # pure-jax reference
    l3 = logits.reshape(B, GROUPS, CLASSES)
    p = jax.nn.softmax(l3, -1)
    p = 0.99 * p + 0.01 / CLASSES
    mixed = jnp.log(p)
    ref_hard = jax.nn.one_hot(jnp.argmax(mixed + noise.reshape(B, GROUPS, CLASSES), -1), CLASSES)
    ref_probs = jax.nn.softmax(mixed, -1)
    out = {
        "ok": bool(
            jnp.allclose(hard.reshape(B, GROUPS, CLASSES), ref_hard, atol=1e-6)
            and jnp.allclose(probs.reshape(B, GROUPS, CLASSES), ref_probs, atol=1e-5)
        ),
        "variant": variant,
        "backend": jax.default_backend(),
        "interpret": interpret,
        "max_prob_err": float(jnp.abs(probs.reshape(B, GROUPS, CLASSES) - ref_probs).max()),
        "hard_mismatch_rows": int(
            (jnp.abs(hard.reshape(B, GROUPS, CLASSES) - ref_hard) > 1e-6).any(-1).any(-1).sum()
        ),
    }
    print(json.dumps(out))
    sys.exit(0 if out["ok"] else 1)


if __name__ == "__main__":
    main()
