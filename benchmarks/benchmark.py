"""Wall-clock one of the `exp=*_benchmarks` workloads through the real CLI
(counterpart of the reference's benchmarks/benchmark.py).

Usage:
    python benchmarks/benchmark.py                 # PPO (the headline)
    python benchmarks/benchmark.py a2c_benchmarks
    python benchmarks/benchmark.py sac_benchmarks
    python benchmarks/benchmark.py dreamer_v3_benchmarks
    # multi-device variants, e.g.:
    python benchmarks/benchmark.py ppo_benchmarks fabric.devices=2 env.num_envs=2

For the driver-facing single-JSON-line benchmark see `bench.py` at the repo
root.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if __name__ == "__main__":
    from sheeprl_tpu.cli import run

    exp = sys.argv[1] if len(sys.argv) > 1 else "ppo_benchmarks"
    overrides = [f"exp={exp}", *sys.argv[2:]]
    tic = time.perf_counter()
    run(overrides)
    print(time.perf_counter() - tic)
