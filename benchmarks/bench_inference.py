"""Inference-service latency/throughput ladder (ISSUE 8 bench).

Measures the serving plane end to end over real queue channels: N worker
threads (standing in for env-worker processes) each fire single-row
observation requests through an :class:`InferenceClient` into one
:class:`InferenceServer`, for a grid of worker counts x batch deadlines.
Per cell: actions/s, request latency p50/p95 (client-observed), and the
server's batch-size histogram (how well the deadline coalesces traffic).
A direct-call LOCAL baseline (same jitted policy, no transport) anchors
the numbers — the remote/local ratio is the price of the hop, which the
centralization pays back by freeing workers from params adoption and by
batching many workers onto one accelerator dispatch.

Single-core caveat (same as bench_fanin): with workers, server thread and
the jitted policy time-slicing one host core, throughput here is a LOWER
bound; the batching effect (bigger buckets at higher worker counts) is
the portable signal.

Standalone::

    python benchmarks/bench_inference.py [--requests 256] [--out results.json]

or as bench.py's ``serve`` section.
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys
import threading
import time

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

OBS_DIM = 8
ACT_DIM = 4
HIDDEN = 64


def _make_policy():
    """A jitted MLP policy of the dummy-env PPO player's scale."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    params = {
        "w1": jnp.asarray(rng.normal(size=(OBS_DIM, HIDDEN)).astype(np.float32) * 0.1),
        "w2": jnp.asarray(rng.normal(size=(HIDDEN, ACT_DIM)).astype(np.float32) * 0.1),
    }

    @jax.jit
    def apply(p, x):
        h = jnp.tanh(x @ p["w1"])
        return jnp.argmax(h @ p["w2"], axis=-1)

    def policy_fn(p, obs, key):
        return {"actions": np.asarray(apply(p, obs["state"]))}

    return policy_fn, params, apply


def _bench_local(apply, params, n_requests: int) -> dict:
    """Direct-call baseline: the same policy, one row per call, no hop."""
    import jax

    x = np.zeros((1, OBS_DIM), np.float32)
    np.asarray(apply(params, x))  # compile
    lats = []
    t0 = time.perf_counter()
    for i in range(n_requests):
        t1 = time.perf_counter()
        np.asarray(apply(params, x + i))
        lats.append(time.perf_counter() - t1)
    wall = time.perf_counter() - t0
    arr = np.sort(np.asarray(lats))
    return {
        "actions_per_s": round(n_requests / wall, 1),
        "latency_ms": {
            "p50": round(float(np.percentile(arr, 50)) * 1e3, 3),
            "p95": round(float(np.percentile(arr, 95)) * 1e3, 3),
        },
    }


def _bench_remote(policy_fn, params, n_workers: int, deadline_ms: float, n_requests: int) -> dict:
    from sheeprl_tpu.parallel.transport import make_transport
    from sheeprl_tpu.serve import InferenceClient, InferenceServer

    ctx = mp.get_context("spawn")
    hub, specs = make_transport(ctx, "queue", n_workers, window=8, min_bytes=0)
    srv = InferenceServer(policy_fn, params, deadline_ms=deadline_ms, max_batch=64)
    clients = [InferenceClient(specs[i].player_channel(), i, request_timeout_s=30.0) for i in range(n_workers)]
    for i in range(n_workers):
        srv.attach(i, hub.channel(i, timeout=5))
    srv.start()

    # warm the buckets so the grid cell measures steady state
    for c in clients:
        c.infer([("state", np.zeros((1, OBS_DIM), np.float32))], 1)

    fails = []

    def drive(cid):
        obs = np.zeros((1, OBS_DIM), np.float32)
        for i in range(n_requests):
            obs[0, 0] = i
            out, src = clients[cid].infer([("state", obs)], 1)
            if src != "remote":
                fails.append(cid)
                return

    threads = [threading.Thread(target=drive, args=(i,)) for i in range(n_workers)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    stats = srv.stats()
    # aggregate the client-observed latency windows
    lat_all = []
    for c in clients:
        p = c.stats()["latency_ms"]
        if p:
            lat_all.append(p)
    out = {
        "workers": n_workers,
        "deadline_ms": deadline_ms,
        "actions_per_s": round(n_workers * n_requests / wall, 1),
        "client_latency_ms": {
            "p50": round(float(np.median([p["p50"] for p in lat_all])), 3),
            "p95": round(float(max(p["p95"] for p in lat_all)), 3),
        },
        "server_latency_ms": stats["latency_ms"],
        "batch_hist": stats["batch_hist"],
        "failures": len(fails),
    }
    srv.close()
    for c in clients:
        c.close()
    hub.close()
    return out


def run_grid(n_requests: int = 256, workers=(1, 2, 4), deadlines=(1.0, 5.0)) -> dict:
    policy_fn, params, apply = _make_policy()
    local = _bench_local(apply, params, n_requests)
    cells = []
    for w in workers:
        for d in deadlines:
            cells.append(_bench_remote(policy_fn, params, w, d, n_requests))
    # headline: best remote throughput across the grid vs the local call
    best = max(cells, key=lambda c: c["actions_per_s"])
    return {
        "local_baseline": local,
        "grid": cells,
        "best_remote": {k: best[k] for k in ("workers", "deadline_ms", "actions_per_s")},
        "remote_over_local_throughput": round(best["actions_per_s"] / local["actions_per_s"], 3),
        "host_cpu_count": os.cpu_count(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=256)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    result = run_grid(n_requests=args.requests)
    text = json.dumps(result, indent=2)
    print(text)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
