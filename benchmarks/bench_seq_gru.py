"""Wall-clock the one-kernel sequence GRU against the per-step scan paths.

Three variants of the decoupled-RSSM dynamic recurrence at DV3-S shapes
(T=64, B=16, H=512, X=512 — the benched anchor config), on the current
default jax platform:

* ``scan``      — lax.scan over the unfused cell (gru_step_gated path)
* ``scan_fused``— lax.scan over the per-step Pallas cell (fused=True)
* ``seq``       — ops/seq_gru.gru_sequence (ONE kernel, weights resident)

Times forward-only and forward+backward (grad wrt weights), since the train
path runs under jax.grad. Writes benchmarks/results/seq_gru_<platform>.json.

Usage: python benchmarks/bench_seq_gru.py [--T 64] [--B 16] [--H 512] [--steps 30]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--T", type=int, default=64)
    ap.add_argument("--B", type=int, default=16)
    ap.add_argument("--H", type=int, default=512)
    ap.add_argument("--X", type=int, default=512)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.ops.pallas_gru import gru_cell
    from sheeprl_tpu.ops.seq_gru import gru_sequence, gru_sequence_reference

    platform = jax.default_backend()
    interpret = platform != "tpu"
    T, B, H, X = args.T, args.B, args.H, args.X
    rng = np.random.default_rng(0)
    h0 = jnp.zeros((B, H))
    xs = jnp.asarray(rng.normal(size=(T, B, X)), jnp.float32)
    w = jnp.asarray(rng.normal(scale=0.05, size=(H + X, 3 * H)), jnp.float32)
    gamma = jnp.ones((3 * H,))
    beta = jnp.zeros((3 * H,))
    is_first = jnp.zeros((T, B, 1)).at[0].set(1.0)
    init_rec = jnp.asarray(rng.normal(size=(B, H)), jnp.float32)

    def scan_plain(w_, xs_):
        return gru_sequence_reference(h0, xs_, w_, gamma, beta, is_first, init_rec)

    def scan_fused(w_, xs_):
        def step(h, inp):
            x, f = inp
            hg = (1.0 - f) * h + f * init_rec
            h_new = gru_cell(hg, x, w_, gamma, beta, 1e-6, True, 8, 512, interpret)
            return h_new, h_new

        _, hs = jax.lax.scan(step, h0, (xs_, is_first))
        return hs

    def seq(w_, xs_):
        return gru_sequence(h0, xs_, w_, gamma, beta, is_first, init_rec, 1e-6, interpret)

    results = {"platform": platform, "T": T, "B": B, "H": H, "X": X, "steps": args.steps}
    for name, fn in (("scan", scan_plain), ("scan_fused", scan_fused), ("seq", seq)):
        fwd = jax.jit(lambda w_, xs_, fn=fn: fn(w_, xs_).sum())
        grad = jax.jit(jax.grad(lambda w_, xs_, fn=fn: fn(w_, xs_).sum()))
        for tag, f in (("fwd", fwd), ("grad", grad)):
            out = f(w, xs)
            jax.block_until_ready(out)
            tic = time.perf_counter()
            for _ in range(args.steps):
                out = f(w, xs)
            jax.block_until_ready(out)
            ms = (time.perf_counter() - tic) / args.steps * 1e3
            results[f"{name}_{tag}_ms"] = round(ms, 3)
            print(f"{name:10s} {tag}: {ms:8.3f} ms", file=sys.stderr)

    out_path = args.out or f"benchmarks/results/seq_gru_{platform}.json"
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
