"""Profile the jitted DreamerV3-S train step and report the top ops.

VERDICT r2 item 3 ("attack the top hotspot") needs a real breakdown of
where the ~30 ms step goes before any kernel work is justified. This
captures a ``jax.profiler`` trace of a few steady-state steps, then
parses the trace-event JSON for the busiest XLA ops on the device.

Run on an IDLE chip (timing noise with a concurrent training run is
+-15%):

    python benchmarks/profile_dv3_step.py [--steps 5] [--out PATH]

Writes benchmarks/results/dv3_profile_r3.json with
{op, total_ms, count, pct_of_top} rows and prints the table.
"""

import argparse
import glob
import gzip
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(steps: int, trace_dir: str):
    import jax

    from benchmarks.bench_dv3_step import build

    runtime, train_fn, params, opt_states, moments, data, (T, B) = build(False, "bf16-mixed")
    params = runtime.replicate(params)
    opt_states = runtime.replicate(opt_states)
    moments = runtime.replicate(moments)
    for _ in range(2):  # compile + warm
        params, opt_states, moments, m = train_fn(params, opt_states, moments, data, runtime.next_key())
    float(jax.tree_util.tree_leaves(m)[0])

    with jax.profiler.trace(trace_dir):
        tic = time.perf_counter()
        for _ in range(steps):
            params, opt_states, moments, m = train_fn(
                params, opt_states, moments, data, runtime.next_key()
            )
        float(jax.tree_util.tree_leaves(m)[0])
        dt = (time.perf_counter() - tic) / steps
    return dt, T * B, (T, B)


def parse_trace(trace_dir: str, top: int = 25):
    """Aggregate device-lane op durations from the trace-event JSON."""
    paths = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True)
    if not paths:
        raise FileNotFoundError(f"no trace.json.gz under {trace_dir}")
    with gzip.open(max(paths, key=os.path.getmtime), "rt") as f:
        events = json.load(f).get("traceEvents", [])
    # device lanes: require an ACCELERATOR marker and exclude host lanes —
    # "/device:CPU:0" and host-side XLA threads would otherwise pollute the
    # "device op" totals that the kernel-work decisions are based on
    device_pids = set()
    names = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pname = ev.get("args", {}).get("name", "")
            names[ev.get("pid")] = pname
            low = pname.lower()
            is_accel = any(k in low for k in ("tpu", "gpu", "accelerator"))
            is_host = ":cpu" in low or "host" in low or "python" in low
            if is_accel and not is_host:
                device_pids.add(ev.get("pid"))
    if not device_pids:
        raise RuntimeError(
            f"no accelerator lanes in trace (process names: {sorted(set(names.values()))[:10]}) — "
            "refusing to aggregate host lanes as device time"
        )
    agg = {}
    for ev in events:
        if ev.get("ph") == "X" and ev.get("pid") in device_pids:
            name = ev.get("name", "?")
            entry = agg.setdefault(name, [0.0, 0])
            entry[0] += float(ev.get("dur", 0.0)) / 1e3  # us -> ms
            entry[1] += 1
    rows = sorted(
        ({"op": k, "total_ms": round(v[0], 2), "count": v[1]} for k, v in agg.items()),
        key=lambda r: -r["total_ms"],
    )
    return rows[:top], {pid: names.get(pid, "") for pid in device_pids}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--trace-dir", default="/tmp/sheeprl_dv3_trace")
    ap.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(__file__), "results", "dv3_profile_r3.json"),
    )
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args()

    dt, frames, shape = capture(args.steps, args.trace_dir)
    rows, lanes = parse_trace(args.trace_dir, args.top)
    total = sum(r["total_ms"] for r in rows) or 1.0
    for r in rows:
        r["pct_of_top"] = round(100.0 * r["total_ms"] / total, 1)
    artifact = {
        "protocol": f"jax.profiler trace of {args.steps} steady-state DV3-S train steps "
        f"(T={shape[0]}, B={shape[1]}, bf16-mixed), device-lane op totals",
        "measured_step_ms": round(dt * 1e3, 1),
        "replayed_frames_per_s": round(frames / dt, 1),
        "device_lanes": lanes,
        "top_ops": rows,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
    for r in rows[:15]:
        print(json.dumps(r))
    print(f"wrote {args.out} (step {artifact['measured_step_ms']} ms)")


if __name__ == "__main__":
    main()
