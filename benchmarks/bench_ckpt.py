"""Checkpoint-plane ladder (ISSUE 17): single-zip vs sharded directory
saves and restores, vs state size x fsdp shard count.

The distributed checkpoint format (``resilience/sharded_ckpt.py``) buys
two things over the single-zip v1 format it dispatches alongside:

* **save fan-out** — each fsdp rank's shard file is written by its own
  double-buffered async writer, so the save wall-clock is bounded by the
  LARGEST shard (plus the manifest stitch), not the whole state.  On a
  real pod the writers are separate processes on separate hosts; on this
  single host the thread-per-shard fan-out is the same code path, so the
  measured win is a LOWER bound set by how much the per-shard zip/fsync
  work overlaps on the available cores (``host_cpu_count`` is recorded).
* **restore locality** — ``load_sharded_slices(f', rank)`` reads only
  the saved shard files that intersect the caller's slice
  (``reshard_plan``), so a resharded restore moves ~1/f' of the bytes a
  full assemble does.

Every (size, fsdp) rung times four legs INTERLEAVED, min-of-N per leg
(same discipline as bench_replay_sampling: interleaving decorrelates the
page-cache and CPU-frequency drift a sequential A-then-B pair would bake
into whichever leg ran second):

* zip ``save_state`` / ``load_state`` — the f=1 baseline pair
* sharded ``save_sharded(f)`` / ``load_sharded`` (global assemble)
* ``load_sharded_slices(f, rank=0)`` — the per-process restore
* ``validate_manifest`` — the refusal matrix's happy-path cost (what
  autoresume pays per candidate before trusting it)

The state is a synthetic model-shaped pytree (square matmul kernels +
bias vectors + scalar step counters, all dims divisible by 8) — the
format never inspects semantics, only shapes, so real agent states at
the same byte count time identically (bench_ckpt_xl.py covers the real
DV3-XL state for the zip path).

Usage: python benchmarks/bench_ckpt.py \
           [--sizes-mb 64 256] [--iters 3] [--fsdp 1 2 4 8] \
           [--out benchmarks/results/ckpt_r17.json]
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_state(total_mb: int, seed: int = 0) -> dict:
    """Model-shaped pytree of ~``total_mb`` MB: (1024, 1024) f32 kernels
    (4 MB each, every dim divisible by 8 so all fsdp sizes shard them
    equally) + small bias vectors + the scalar bookkeeping leaves a real
    ``ckpt_state`` carries."""
    rng = np.random.default_rng(seed)
    n_layers = max(1, total_mb // 4)
    params = {
        f"layer_{i}": {
            "kernel": rng.standard_normal((1024, 1024), dtype=np.float32),
            "bias": rng.standard_normal((1024,), dtype=np.float32),
        }
        for i in range(n_layers)
    }
    return {"params": params, "iter_num": 1234, "batch_size": 64}


def _timed(fn, timings: list) -> None:
    tic = time.perf_counter()
    fn()
    timings.append(time.perf_counter() - tic)


def run_ladder(sizes_mb=(64, 256), fsdp_sizes=(1, 2, 4, 8), n_iters=3) -> list:
    from sheeprl_tpu.resilience.sharded_ckpt import (
        load_sharded,
        load_sharded_slices,
        save_sharded,
        validate_manifest,
    )
    from sheeprl_tpu.utils.ckpt_format import load_state, save_state

    rows = []
    for size_mb in sizes_mb:
        state = build_state(size_mb)
        actual_mb = (
            sum(l["kernel"].nbytes + l["bias"].nbytes for l in state["params"].values())
            / 1e6
        )
        root = tempfile.mkdtemp(prefix=f"bench_ckpt_{size_mb}_")
        zip_path = os.path.join(root, "state.ckpt")
        legs = {f: {"save": [], "load": [], "slice": [], "validate": []} for f in fsdp_sizes}
        zip_save, zip_load = [], []
        stats_by_f = {}
        try:
            for _ in range(n_iters):
                # interleaved: one full pass of every leg per iteration
                _timed(lambda: save_state(zip_path, state), zip_save)
                _timed(lambda: load_state(zip_path), zip_load)
                for f in fsdp_sizes:
                    dpath = os.path.join(root, f"state_f{f}.dckpt")
                    shutil.rmtree(dpath, ignore_errors=True)
                    tic = time.perf_counter()
                    stats_by_f[f] = save_sharded(dpath, state, fsdp_size=f)
                    legs[f]["save"].append(time.perf_counter() - tic)
                    _timed(lambda d=dpath: validate_manifest(d), legs[f]["validate"])
                    _timed(lambda d=dpath: load_sharded(d), legs[f]["load"])
                    _timed(
                        lambda d=dpath, ff=f: load_sharded_slices(d, ff, 0),
                        legs[f]["slice"],
                    )
        finally:
            shutil.rmtree(root, ignore_errors=True)
        sharded_rows = []
        for f in fsdp_sizes:
            st = stats_by_f[f]
            sharded_rows.append(
                {
                    "fsdp": f,
                    "save_s": round(min(legs[f]["save"]), 4),
                    "load_s": round(min(legs[f]["load"]), 4),
                    "slice_load_s": round(min(legs[f]["slice"]), 4),
                    "validate_s": round(min(legs[f]["validate"]), 4),
                    # from the save's own stats: the slowest single shard
                    # writer (= the pod-scale save wall-clock, where each
                    # shard has its own host) + the manifest stitch
                    "max_shard_write_s": round(st["max_shard_write_s"], 4),
                    "stitch_s": round(st["stitch_s"], 4),
                }
            )
        rows.append(
            {
                "size_mb": round(actual_mb, 1),
                "zip_save_s": round(min(zip_save), 4),
                "zip_load_s": round(min(zip_load), 4),
                "sharded": sharded_rows,
            }
        )
    return rows


def summarize(rows: list) -> dict:
    """Headline ratios off the largest-size rung, widest fsdp."""
    top = rows[-1]
    widest = top["sharded"][-1]
    return {
        "size_mb": top["size_mb"],
        "fsdp": widest["fsdp"],
        # single-host wall ratio (thread fan-out; lower-bound on a small box)
        "zip_over_sharded_save": round(top["zip_save_s"] / widest["save_s"], 3),
        # pod-scale ratio: each shard writer on its own host, so the save
        # costs max-shard + stitch
        "zip_over_max_shard_save": round(
            top["zip_save_s"] / (widest["max_shard_write_s"] + widest["stitch_s"]), 3
        ),
        # restore locality: full assemble vs one rank's slices
        "full_load_over_slice_load": round(widest["load_s"] / widest["slice_load_s"], 3),
        "host_cpu_count": os.cpu_count(),
    }


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--sizes-mb", type=int, nargs="+", default=[64, 256])
    parser.add_argument("--fsdp", type=int, nargs="+", default=[1, 2, 4, 8])
    parser.add_argument("--iters", type=int, default=3)
    parser.add_argument("--out", default=None, help="write the result JSON here")
    args = parser.parse_args()

    rows = run_ladder(tuple(args.sizes_mb), tuple(args.fsdp), args.iters)
    result = {"rows": rows, "summary": summarize(rows)}
    print(json.dumps(result, indent=2))
    if args.out:
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
