"""DV1/DV2 train-step micro-benchmark on the current default jax platform.

Companion to ``bench_dv3_step.py`` for the other two Dreamer generations:
builds each algo's full single-jit train step at its default model size on
Atari-shaped pixels (64x64x3, discrete 6 actions, the exp yaml's
per_rank batch/sequence: DV1 50x50, DV2 16x50) and times steady-state
dispatch the way the training CLI runs it (chained async dispatches, one
trailing sync).

Round-4 context: the DV3 scan-path optimizations (RNG hoisting, prior
hoisting, remat policies) were propagated to DV1/DV2 mechanically; this
harness produces the chip numbers for that claim.

Usage: python benchmarks/bench_dreamer_family_step.py \
           [--precision bf16-mixed] [--steps 20] [--algos dreamer_v1,dreamer_v2] \
           [--out benchmarks/results/dreamer_family_step.json]
"""

import argparse
import importlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def time_algo(name: str, precision: str, steps: int, extra_overrides=(), accelerator="auto"):
    import gymnasium as gym
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.config import compose
    from sheeprl_tpu.parallel.mesh import MeshRuntime

    agent_mod = importlib.import_module(f"sheeprl_tpu.algos.{name}.agent")
    algo_mod = importlib.import_module(f"sheeprl_tpu.algos.{name}.{name}")

    cfg = compose(
        overrides=[
            f"exp={name}",
            "env=dummy",
            "env.num_envs=1",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            *extra_overrides,
        ]
    )
    # NOTE: "auto" initializes the axon TPU plugin even under
    # JAX_PLATFORMS=cpu — pass --accelerator cpu for host-only smoke runs
    # (a stray bench on the chip competes with whatever is training there)
    runtime = MeshRuntime(devices=1, accelerator=accelerator, precision=precision).launch()
    runtime.seed_everything(0)
    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (64, 64, 3), np.uint8)})
    actions_dim = (6,)
    world_model, actor, critic, params = agent_mod.build_agent(
        runtime, actions_dim, False, cfg, obs_space
    )
    params = runtime.to_param_dtype(params, exclude=("target_critic",))
    mk = algo_mod._make_optimizer
    txs = (
        mk(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients, precision),
        mk(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients, precision),
        mk(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients, precision),
    )
    opt_states = {
        "world_model": txs[0].init(params["world_model"]),
        "actor": txs[1].init(params["actor"]),
        "critic": txs[2].init(params["critic"]),
    }
    train_fn = algo_mod.make_train_fn(
        runtime, world_model, actor, critic, txs, cfg, False, actions_dim
    )

    T = int(cfg.algo.per_rank_sequence_length)
    B = int(cfg.algo.per_rank_batch_size)
    rng = np.random.default_rng(0)
    data = {
        "rgb": jnp.asarray(rng.integers(0, 255, (T, B, 64, 64, 3)).astype(np.float32)),
        "actions": jnp.asarray(np.eye(6, dtype=np.float32)[rng.integers(0, 6, (T, B))]),
        "rewards": jnp.asarray(rng.normal(size=(T, B, 1)).astype(np.float32)),
        "terminated": jnp.zeros((T, B, 1), jnp.float32),
        "is_first": jnp.zeros((T, B, 1), jnp.float32),
    }
    params = runtime.replicate(params)
    opt_states = runtime.replicate(opt_states)
    for _ in range(2):  # compile + cache-stability proof
        params, opt_states, metrics = train_fn(params, opt_states, data, runtime.next_key())
        float(jax.tree_util.tree_leaves(metrics)[0])
    tic = time.perf_counter()
    for _ in range(steps):
        params, opt_states, metrics = train_fn(params, opt_states, data, runtime.next_key())
    float(jax.tree_util.tree_leaves(metrics)[0])
    dt = (time.perf_counter() - tic) / steps
    # the actual compute device, NOT jax.default_backend() (which reports
    # the process default even when the runtime pinned compute elsewhere)
    device = next(iter(jax.tree_util.tree_leaves(params)[0].devices()))
    print(
        f"{name} [{device.platform}]: {dt * 1e3:.1f} ms/step, "
        f"{T * B / dt:,.0f} replayed frames/s (T={T}, B={B})",
        file=sys.stderr,
    )
    return {
        "step_ms": round(dt * 1e3, 2),
        "replayed_frames_per_s": round(T * B / dt, 1),
        "T": T,
        "B": B,
        "platform": device.platform,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--precision", default="bf16-mixed")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--algos", default="dreamer_v1,dreamer_v2")
    ap.add_argument("--out", default="benchmarks/results/dreamer_family_step.json")
    ap.add_argument("--accelerator", default="auto", help="cpu forces host-only (smoke tests)")
    ap.add_argument("overrides", nargs="*", help="extra config overrides (smoke tests)")
    args = ap.parse_args()

    import jax

    results = {
        "precision": args.precision,
        "protocol": (
            "single-jit train step, default exp per_rank shapes on 64x64x3 "
            "pixels + discrete(6); steady state over chained async "
            f"dispatches, {args.steps} steps after 2 warmups"
        ),
    }
    for name in args.algos.split(","):
        results[name] = time_algo(
            name.strip(), args.precision, args.steps, tuple(args.overrides), args.accelerator
        )
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
