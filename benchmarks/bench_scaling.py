"""Virtual-mesh scaling table: PPO, SAC, and DreamerV3 jitted-step
wall-clock at 1/2/4/8 mesh devices (BASELINE.md's "PPO FPS 1->16 chips"
stand-in).

All "devices" here are XLA host-platform devices sharing ONE physical
core, so wall-clock cannot improve with mesh size; what the table
validates is the OVERHEAD of the SPMD path: with the global batch fixed
(strong scaling), total FLOPs are constant, so ideal sharding keeps the
normalized step time at ~1.0 at every mesh size — anything above that is
partitioning/collective overhead that would also tax a real pod.  Run on
real multi-chip hardware the same script measures true scaling.

Writes benchmarks/results/scaling_r3.json and prints one JSON line per
(algo, devices) pair.

Usage:  python benchmarks/bench_scaling.py  [--steps N] [--out PATH]
(spawns nothing; force the virtual mesh with
 XLA_FLAGS=--xla_force_host_platform_device_count=8).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax

# the machine env preimports jax pinned to the accelerator tunnel (same
# dance as tests/conftest.py); the scaling mesh must be host CPU devices
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

MESH_SIZES = (1, 2, 4, 8)


def _time_step(step, carry, n_warm=2, n_steps=10):
    """``step(carry) -> carry`` with every donated buffer threaded through
    the carry — reusing a donated input crashes with 'buffer deleted'."""
    for _ in range(n_warm):
        carry = step(carry)
        jax.block_until_ready(carry)
    tic = time.perf_counter()
    for _ in range(n_steps):
        carry = step(carry)
    jax.block_until_ready(carry)
    return (time.perf_counter() - tic) / n_steps


def bench_ppo(devices: int, steps: int):
    """Full PPO update (GAE + epochs x minibatches) on a `devices`-wide
    data-parallel mesh; global rollout fixed at T=128 x 64 envs."""
    import gymnasium as gym

    from sheeprl_tpu.algos.ppo.agent import build_agent
    from sheeprl_tpu.algos.ppo.ppo import build_ppo_optimizer, make_update_fn
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.parallel.mesh import MeshRuntime

    cfg = compose(
        overrides=[
            "exp=ppo",
            "env=dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            "env.num_envs=64",
            "algo.rollout_steps=128",
            "algo.per_rank_batch_size=256",
            "algo.update_epochs=2",
        ]
    )
    runtime = MeshRuntime(devices=devices, accelerator="cpu").launch()
    runtime.seed_everything(0)
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-1, 1, (64,), np.float32)})
    module, params = build_agent(runtime, (4,), False, cfg, obs_space)
    params = runtime.replicate(params)
    tx = build_ppo_optimizer(cfg.algo.optimizer, cfg.algo.max_grad_norm, runtime.precision)
    opt_state = runtime.replicate(tx.init(params))
    update_fn = make_update_fn(runtime, module, tx, cfg, ["state"])

    T, E = 128, 64
    rng = np.random.default_rng(0)
    data = {
        "state": jnp.asarray(rng.normal(size=(T, E, 64)).astype(np.float32)),
        "values": jnp.asarray(rng.normal(size=(T, E, 1)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(T, E, 1)).astype(np.float32)),
        "dones": jnp.zeros((T, E, 1), jnp.float32),
        "logprobs": jnp.asarray(rng.normal(size=(T, E, 1)).astype(np.float32)),
        "actions": jnp.asarray(rng.integers(0, 4, size=(T, E, 1)).astype(np.float32)),
    }
    data = runtime.shard_batch(data, axis=1)  # DP over the env axis
    next_obs = runtime.shard_batch(
        {"state": jnp.asarray(rng.normal(size=(E, 64)).astype(np.float32))}, axis=0
    )

    def step(carry):
        params, opt_state = carry
        params, opt_state, _ = update_fn(
            params, opt_state, data, next_obs, runtime.next_key(),
            jnp.float32(0.2), jnp.float32(0.0), jnp.float32(3e-4),
        )
        return params, opt_state

    dt = _time_step(step, (params, opt_state), n_steps=steps)
    return dt, T * E


def bench_dv3(devices: int, steps: int):
    """Compact DreamerV3 train step (wm + imagination + actor + critic) on
    a `devices`-wide mesh; global batch fixed at B=16 x T=16 pixels."""
    import gymnasium as gym

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import _make_optimizer, make_train_fn
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.parallel.mesh import MeshRuntime

    cfg = compose(
        overrides=[
            "exp=dreamer_v3",
            "env=dummy",
            "env.num_envs=1",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.per_rank_batch_size=16",
            "algo.per_rank_sequence_length=8",
            "algo.horizon=4",
            "algo.world_model.recurrent_model.recurrent_state_size=128",
            "algo.world_model.representation_model.hidden_size=128",
            "algo.world_model.transition_model.hidden_size=128",
            "algo.world_model.encoder.cnn_channels_multiplier=4",
            "algo.dense_units=128",
            "algo.mlp_layers=1",
        ]
    )
    runtime = MeshRuntime(devices=devices, accelerator="cpu").launch()
    runtime.seed_everything(0)
    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (64, 64, 3), np.uint8)})
    world_model, actor, critic, params = build_agent(runtime, (6,), True, cfg, obs_space)
    params = runtime.replicate(params)
    wm_tx = _make_optimizer(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
    actor_tx = _make_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_tx = _make_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    opt_states = runtime.replicate(
        {
            "world_model": wm_tx.init(params["world_model"]),
            "actor": actor_tx.init(params["actor"]),
            "critic": critic_tx.init(params["critic"]),
        }
    )
    moments = runtime.replicate(init_moments())
    train_fn = make_train_fn(
        runtime, world_model, actor, critic, (wm_tx, actor_tx, critic_tx), cfg, True, (6,)
    )
    T, B = 8, 16
    rng = np.random.default_rng(0)
    data = {
        "rgb": jnp.asarray(rng.integers(0, 255, size=(T, B, 64, 64, 3), dtype=np.uint8)),
        "actions": jnp.asarray(rng.normal(size=(T, B, 6)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(T, B, 1)).astype(np.float32)),
        "terminated": jnp.zeros((T, B, 1), jnp.float32),
        "truncated": jnp.zeros((T, B, 1), jnp.float32),
        "is_first": jnp.zeros((T, B, 1), jnp.float32),
    }
    data = runtime.shard_batch(data, axis=1)

    def step(carry):
        params, opt_states, moments = carry
        params, opt_states, moments, _ = train_fn(
            params, opt_states, moments, data, runtime.next_key()
        )
        return params, opt_states, moments

    dt = _time_step(step, (params, opt_states, moments), n_steps=steps)
    return dt, T * B


def bench_sac(devices: int, steps: int):
    """SAC scan dispatch (G=8 gradient steps per call, twin critics, alpha
    autotune) on a `devices`-wide mesh; global batch fixed at 8 x 512
    vector rows (the GSPMD path: batch-axis sharding, psum'd grads)."""
    import gymnasium as gym

    from sheeprl_tpu.algos.sac.agent import build_agent
    from sheeprl_tpu.algos.sac.sac import _make_optimizer, make_train_fn
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.parallel.mesh import MeshRuntime

    cfg = compose(
        overrides=[
            "exp=sac",
            "env=dummy",
            "env.id=dummy_continuous",
            "algo.mlp_keys.encoder=[state]",
        ]
    )
    runtime = MeshRuntime(devices=devices, accelerator="cpu").launch()
    runtime.seed_everything(0)
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-1, 1, (16,), np.float32)})
    act_space = gym.spaces.Box(-1, 1, (4,), np.float32)
    actor, critic, params, target_entropy = build_agent(runtime, cfg, obs_space, act_space)
    params = runtime.replicate(params)
    actor_tx = _make_optimizer(cfg.algo.actor.optimizer)
    critic_tx = _make_optimizer(cfg.algo.critic.optimizer)
    alpha_tx = _make_optimizer(cfg.algo.alpha.optimizer)
    opt_states = runtime.replicate(
        {
            "actor": actor_tx.init(params["actor"]),
            "critic": critic_tx.init(params["critic"]),
            "alpha": alpha_tx.init(params["log_alpha"]),
        }
    )
    train_fn = make_train_fn(
        runtime, actor, critic, (actor_tx, critic_tx, alpha_tx), cfg, target_entropy
    )
    G, B = 8, 512
    rng = np.random.default_rng(0)
    data = {
        "observations": jnp.asarray(rng.normal(size=(G, B, 16)).astype(np.float32)),
        "next_observations": jnp.asarray(rng.normal(size=(G, B, 16)).astype(np.float32)),
        "actions": jnp.asarray(rng.normal(size=(G, B, 4)).astype(np.float32)),
        "rewards": jnp.asarray(rng.normal(size=(G, B, 1)).astype(np.float32)),
        "terminated": jnp.zeros((G, B, 1), jnp.float32),
    }
    data = runtime.shard_batch(data, axis=1)
    ema_flags = jnp.asarray(np.array([True] + [False] * (G - 1)))

    def step(carry):
        params, opt_states = carry
        params, opt_states, _ = train_fn(params, opt_states, data, runtime.next_key(), ema_flags)
        return params, opt_states

    dt = _time_step(step, (params, opt_states), n_steps=steps)
    return dt, G * B


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(__file__), "results", "scaling_r3.json"),
    )
    args = ap.parse_args()

    if len(jax.devices()) < max(MESH_SIZES):
        raise RuntimeError(
            f"need {max(MESH_SIZES)} host devices; set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={max(MESH_SIZES)}"
        )

    results = {"protocol": (
        "strong scaling on XLA host-platform virtual devices (one physical core): "
        "global batch fixed, normalized step time ~1.0 at every mesh size = "
        "zero-overhead sharding; >1.0 = partition/collective overhead"
    ), "algos": {}}
    for name, fn in (("ppo", bench_ppo), ("sac", bench_sac), ("dreamer_v3", bench_dv3)):
        base = None
        rows = []
        for n in MESH_SIZES:
            dt, global_items = fn(n, args.steps)
            base = base or dt
            row = {
                "devices": n,
                "step_ms": round(dt * 1e3, 1),
                "normalized_vs_1dev": round(dt / base, 3),
                "global_items_per_step": global_items,
            }
            rows.append(row)
            print(json.dumps({"algo": name, **row}), flush=True)
        results["algos"][name] = rows

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
