"""Time the v1 leaf-manifest checkpoint format on a DreamerV3-XL state.

VERDICT r3 item 7 asked for the stable checkpoint format to be "timed at
XL": the S-scale numbers (1.45 GB: save 11.8 s / load 10.0 s vs 26.8 s
pickle) say nothing about how the format behaves at the 13 GB-HBM XL
scale (dv3_xl_step_r3.json), where a whole-state pickle is the difference
between a tolerable and an unusable checkpoint cadence.

Builds the REAL XL agent (algo=dreamer_v3_XL shapes, reference
configs/algo/dreamer_v3_XL.yaml parity: 4096 GRU, 1024 dense, 96-channel
CNN) plus its three optimizer states on the host CPU, assembles the exact
``ckpt_state`` dict the training loop saves (dreamer_v3.py:929-941, minus
the replay buffer — buffer persistence is covered by the S-scale
measurements and scales with ``buffer.size`` not model size), and times:

* v1 ``save_state`` / full ``load_checkpoint``
* v1 partial read  (``select=("iter_num", "batch_size")``)
* cloudpickle save / load of the same state (the format it replaced)

Usage: JAX_PLATFORMS=cpu python benchmarks/bench_ckpt_xl.py \
           [--out benchmarks/results/ckpt_xl_timing_r4.json]
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_xl_state():
    import gymnasium as gym
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import _make_optimizer
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments
    from sheeprl_tpu.config import compose
    from sheeprl_tpu.parallel.mesh import MeshRuntime

    cfg = compose(
        overrides=[
            "exp=dreamer_v3",
            "env=dummy",
            "algo=dreamer_v3_XL",
            "env.num_envs=1",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
        ]
    )
    runtime = MeshRuntime(devices=1, accelerator="cpu", precision="32-true").launch()
    runtime.seed_everything(0)
    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (64, 64, 3), np.uint8)})
    world_model, actor, critic, params = build_agent(runtime, (6,), False, cfg, obs_space)
    wm_tx = _make_optimizer(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
    actor_tx = _make_optimizer(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_tx = _make_optimizer(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    opt_states = {
        "world_model": wm_tx.init(params["world_model"]),
        "actor": actor_tx.init(params["actor"]),
        "critic": critic_tx.init(params["critic"]),
    }
    # the exact training-loop state dict (dreamer_v3.py ckpt_state), sans rb
    state = {
        "world_model": params["world_model"],
        "actor": params["actor"],
        "critic": params["critic"],
        "target_critic": params["target_critic"],
        "opt_states": opt_states,
        "moments": init_moments(),
        "ratio": {"_ratio": 0.3, "_prev": 123456, "_pretrain_steps": 0},
        "iter_num": 123456,
        "batch_size": 16,
        "last_log": 120000,
        "last_checkpoint": 120000,
    }
    state = jax.device_get(state)
    n_bytes = sum(
        x.nbytes for x in jax.tree_util.tree_leaves(state) if hasattr(x, "nbytes")
    )
    n_leaves = len(jax.tree_util.tree_leaves(state))
    return state, n_bytes, n_leaves


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="benchmarks/results/ckpt_xl_timing_r4.json")
    args = ap.parse_args()

    t0 = time.perf_counter()
    state, n_bytes, n_leaves = build_xl_state()
    build_s = time.perf_counter() - t0
    print(f"built XL state: {n_bytes / 1e9:.2f} GB, {n_leaves} leaves, {build_s:.1f} s")

    from sheeprl_tpu.utils.callback import load_checkpoint
    from sheeprl_tpu.utils.ckpt_format import save_state

    import cloudpickle

    results = {
        "protocol": (
            "DreamerV3-XL ckpt_state (params + 3 adam opt states + counters, no "
            "replay buffer) built on host CPU; save/load on local disk, "
            "best of 2 runs each"
        ),
        "state_gb": round(n_bytes / 1e9, 3),
        "n_leaves": n_leaves,
    }

    with tempfile.TemporaryDirectory() as td:
        v1 = os.path.join(td, "xl_v1.ckpt")
        pk = os.path.join(td, "xl_pickle.ckpt")

        for _ in range(2):
            t0 = time.perf_counter()
            save_state(v1, state)
            results["v1_save_s"] = min(
                results.get("v1_save_s", 1e9), round(time.perf_counter() - t0, 2)
            )
        results["v1_file_gb"] = round(os.path.getsize(v1) / 1e9, 3)

        for _ in range(2):
            t0 = time.perf_counter()
            loaded = load_checkpoint(v1)
            results["v1_load_full_s"] = min(
                results.get("v1_load_full_s", 1e9), round(time.perf_counter() - t0, 2)
            )
        assert loaded["iter_num"] == state["iter_num"]
        del loaded

        for _ in range(2):
            t0 = time.perf_counter()
            partial = load_checkpoint(v1, select=("iter_num", "batch_size"))
            results["v1_load_select_ms"] = min(
                results.get("v1_load_select_ms", 1e9),
                round((time.perf_counter() - t0) * 1e3, 1),
            )
        assert partial["iter_num"] == state["iter_num"]

        for _ in range(2):
            t0 = time.perf_counter()
            with open(pk, "wb") as f:
                cloudpickle.dump(state, f)
            results["pickle_save_s"] = min(
                results.get("pickle_save_s", 1e9), round(time.perf_counter() - t0, 2)
            )
        results["pickle_file_gb"] = round(os.path.getsize(pk) / 1e9, 3)

        for _ in range(2):
            t0 = time.perf_counter()
            with open(pk, "rb") as f:
                loaded = cloudpickle.load(f)
            results["pickle_load_s"] = min(
                results.get("pickle_load_s", 1e9), round(time.perf_counter() - t0, 2)
            )
        del loaded

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
