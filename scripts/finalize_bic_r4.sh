#!/bin/bash
# End-of-chain pipeline for the round-4 ball_in_cup-catch run: stitch the
# reward curve across legs, greedy-eval the newest checkpoint, and fold
# the eval into the curve artifact. Run AFTER the chain has stopped.
# FROZEN RECORD: this script already produced its committed artifact and
# is kept as the exact pipeline that made it. New runs should use the
# shared scripts/finalize_curve.py instead (see finalize_dv2_walker_r4.sh
# for the wrapper pattern).
set -e -o pipefail
cd /root/repo
OUT=benchmarks/results/dv3_ball_in_cup_catch_curve_r4.json

# the chain trained FROM SCRATCH in chain_r4 (no r3 legs exist on this
# machine, and stitching another run's logs would corrupt the
# from-scratch curve this artifact claims to be)
python scripts/curve_from_logs.py \
  --chain-dir runs/dv3_bic/chain_r4 \
  --out "$OUT"

CKPT=$(python - <<'EOF'
from scripts.train_chain import latest_ckpt
step, ckpt = latest_ckpt("runs/dv3_bic")
print(ckpt)
EOF
)
if [ -z "$CKPT" ] || [ "$CKPT" = "None" ]; then
  echo "ERROR: no checkpoint found under runs/dv3_bic" >&2
  exit 1
fi
# the run-dir is shared across chains: make sure the newest checkpoint
# actually belongs to the r4 curve being finalized (within one
# checkpoint/log cadence of the stitched final step)
CKPT_STEP=$(basename "$CKPT" | sed -E 's/ckpt_([0-9]+)_.*/\1/')
FINAL_STEP=$(python -c "import json,sys; print(json.load(open('$OUT'))['final_step'])")
DELTA=$((CKPT_STEP - FINAL_STEP)); DELTA=${DELTA#-}
if [ "$DELTA" -gt 8000 ]; then
  echo "ERROR: newest ckpt step $CKPT_STEP is $DELTA steps from the curve's final step $FINAL_STEP — wrong chain's checkpoint?" >&2
  exit 1
fi
echo "evaluating $CKPT"
MUJOCO_GL=egl timeout 1200 python sheeprl_eval.py "checkpoint_path=$CKPT" \
  env.capture_video=False 2>&1 | tee /tmp/bic_eval_r4.log | tail -3

python - "$OUT" <<'EOF'
import glob, json, re, sys
out = sys.argv[1]
d = json.load(open(out))
txt = open("/tmp/bic_eval_r4.log").read()
m = re.findall(r"Test - Reward: ([-\d.]+)", txt)
d["greedy_eval_reward_at_final_ckpt"] = float(m[-1]) if m else None
# per-leg throughput: legs 0-2 ran the host feed path, legs 3+ the HBM
# replay cache (data/device_buffer.py) — the sps jump is the real-run
# evidence for benchmarks/results/device_cache_r4.json
legs = {}
for p in sorted(glob.glob("runs/dv3_bic/chain_r4/leg_*.log")):
    hb = re.findall(
        r"heartbeat policy_step=(\d+), sps=([\d.]+), gradient_steps=\d+, env_s=([\d.]+), train_s=([\d.]+)",
        open(p, errors="ignore").read(),
    )
    if hb:
        leg = re.search(r"leg_(\d+)", p).group(1)
        legs[leg] = [
            {"step": int(s), "sps": float(r), "env_s": float(e), "train_s": float(t)}
            for s, r, e, t in hb[-3:]
        ]
d["per_leg_throughput"] = legs
d["throughput_note"] = (
    "all legs ran with the HBM replay cache (data/device_buffer.py); compare the "
    "cartpole artifact's host-feed legs (~2 sps) for the before/after"
)
json.dump(d, open(out, "w"), indent=2)
print(json.dumps({k: d[k] for k in ("final_step", "final_reward_mean", "best_reward_mean", "greedy_eval_reward_at_final_ckpt")}))
EOF
