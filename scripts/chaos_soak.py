"""Chaos soak for the self-healing N-player topology AND the training
health sentinel.

``--mode topology`` (default) drives one decoupled run under a RANDOMIZED
kill/restart schedule built from the existing ``SHEEPRL_FAULTS`` sites
(player_exit entries at random iterations against random players,
optional net_drop/net_delay noise on the tcp transport), with the
supervisor armed so every kill turns into a backoff-restart-rejoin
cycle.  After the run it audits the lead's telemetry: the pool must
RECOVER to the launch size, every scheduled kill must appear as a death,
rejoins must match, the trainer must not have retraced XLA after warmup
(mask-padded fan-in), and the final reward must be finite.

``--mode health`` is the ISSUE 7 acceptance harness: with ``nan_inject``
armed (``--fault`` picks nan_inject/loss_spike/rb_corrupt), a coupled
SAC run and an N=2 decoupled PPO run must both detect the anomaly within
one update, skip it, trip the consecutive-skip budget, roll back to the
last good checkpoint, and finish rc=0 — with the ``health`` telemetry
key recording the verdicts and the rollback event (and the transport
stats recording the rollback broadcast round for the decoupled run).

Topology acceptance (ISSUE 6) runnable standalone::

    python scripts/chaos_soak.py --players 4 --transport tcp --kills 3 \
        --total-steps 19200 --seed 7

``--mode serve`` is the ISSUE 8 acceptance harness: an
``algo.inference=remote`` N-player run under a randomized server-kill
schedule (+ tcp net noise) — every kill must show breaker trip -> local
fallback -> supervisor respawn -> half-open re-promotion with a clean
request-id audit — plus a deterministic sub-leg offering the hot-swap
watcher a nan-POISONED checkpoint (must be refused) and a good one
(must swap).

Health acceptance (ISSUE 7)::

    python scripts/chaos_soak.py --mode health --seed 7

``--mode integrity`` is the ISSUE 10 acceptance harness: on each of the
three transports, a decoupled run under injected ``bit_flip`` faults
(data frames at both players + a lead-directed params broadcast) must
DETECT every flip at the receive boundary (``integrity`` telemetry:
corrupt_detected >= injected, silent_accepted == 0), recover via the
retransmit / digest-skip machinery (retrans_failed == 0) and finish
rc=0; plus an rb_insert leg (``rb_corrupt`` quarantined at ingest) and
a paired off-vs-crc leg whose final agent params must be bit-exact.

``--mode ckpt`` is the ISSUE 17 acceptance harness: an fsdp (4x2 mesh)
a2c run with ``checkpoint.sharded=true`` is SIGKILLed mid-shard-write
(``ckpt_shard_kill``) — the manifest never commits, so the directory
stays partial — then the SAME root is relaunched with
``checkpoint.resume_from=auto`` onto a DIFFERENT mesh (2x4): auto-resume
must refuse the partial directory, resume from the last COMPLETE
manifest, reshard the restored state onto the new fsdp axis, and finish
rc=0 — with the ``ckpt`` telemetry key carrying the per-shard write /
manifest stitch stats in both phases.

``--mode scale`` is the ISSUE 20 acceptance harness, two legs.  A
decoupled run starts its player pool at the autoscaler MINIMUM (1 of
3); forced gather pressure makes the telemetry-driven autoscaler grow
it through the real supervisor spawn path, and the initially-spawned
player is killed a few iterations in, while the pool is still scaling
up — the pool must still converge to the maximum with the kill
restarted, every decision a typed ``autoscale`` flight event.  Then a session swarm thrashes an elastic
serve pool whose session cache is smaller than the client count — every
client must ride out the ``session_lost`` storm by reopen-and-replay
with zero drops — and a nan-poisoned hot-swap candidate must be refused
by the session server.

Serve acceptance (ISSUE 8)::

    python scripts/chaos_soak.py --mode serve --seed 7

Integrity acceptance (ISSUE 10)::

    python scripts/chaos_soak.py --mode integrity --seed 7

Sharded-checkpoint acceptance (ISSUE 17)::

    python scripts/chaos_soak.py --mode ckpt --seed 7

Elastic-scale acceptance (ISSUE 20)::

    python scripts/chaos_soak.py --mode scale --seed 7

all wrapped by ``chaos``/``slow``-marked pytest soaks.  The schedules
are pure functions of ``--seed``, so a failing soak reproduces exactly.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import random
import sys

# runnable as `python scripts/chaos_soak.py`: sys.path[0] is scripts/,
# the package lives one level up
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def build_kill_schedule(
    rng: random.Random, players: int, kills: int, first_iter: int = 3, span: int = 60
):
    """Randomized but reproducible ``player_exit`` entries: ``kills``
    distinct (iteration, player) pairs.  Player 0 (the lead) is eligible
    too — a lead death exercises the logger/checkpoint re-mastering path.
    Iterations are spread out so each death can complete its
    restart-rejoin cycle before the next one lands."""
    entries = []
    used_pids = []
    for k in range(kills):
        pid = rng.randrange(players)
        at = first_iter + k * span + rng.randrange(span // 2)
        entries.append(f"player_exit:{at}:{pid}")
        used_pids.append(pid)
    return entries, used_pids


def build_net_noise(rng: random.Random, n_drops: int, n_delays: int):
    entries = []
    for _ in range(n_drops):
        entries.append(f"net_drop:{rng.randrange(5, 200)}")
    for _ in range(n_delays):
        entries.append(f"net_delay:{rng.randrange(5, 200)}:{rng.uniform(0.05, 0.3):.2f}")
    return entries


def read_telemetry(root_dir: str):
    """Every ``transport``-keyed record + reward/compile scalars from the
    run's telemetry JSONL files (shared reader: obs/reader.py)."""
    from sheeprl_tpu.obs.reader import iter_run_records

    transports, compiles = [], []
    for rec in iter_run_records(root_dir):
        if "transport" in rec:
            transports.append(rec["transport"])
        if rec.get("trainer_compiles") is not None:
            compiles.append(rec["trainer_compiles"])
    return transports, compiles


def audit(transports, compiles, *, players: int, kills: int, min_rejoins: int = 2) -> list:
    """Return a list of failure strings (empty = soak passed).

    Cumulative counters (supervisor restarts, rejoins) are taken as the
    MAX over all records: while the LEAD itself is dead there is a
    telemetry gap, so the final record can predate the last cycle.  The
    net-noise entries can kill players beyond the schedule (a reconnect
    that misses its window is a real death), so restarts >= kills is the
    every-kill-was-acted-on check, not an equality."""
    failures = []
    if not transports:
        return ["no transport telemetry found (did the lead die without re-mastering?)"]
    last = transports[-1]
    pool = last["live"] + last.get("joining", 0)
    if pool < players:
        failures.append(f"pool never recovered: live+joining={pool} < {players}")
    restarts = max((t.get("supervisor") or {}).get("restarts", 0) for t in transports)
    if restarts < kills:
        failures.append(f"only {restarts} restarts for {kills} scheduled kills")
    rejoins = max(t.get("rejoins", 0) for t in transports)
    if rejoins < min_rejoins:
        failures.append(f"only {rejoins} rejoins observed (need >= {min_rejoins})")
    # zero post-warmup recompiles: the compile counter must plateau (the
    # mask-padded fan-in absorbs every shrink/grow without a retrace)
    if len(compiles) >= 3 and compiles[-1] != compiles[1]:
        failures.append(
            f"trainer retraced XLA after warmup: compiles {compiles[1]} -> {compiles[-1]}"
        )
    return failures


def read_health(root_dir: str):
    """All ``health`` sections (top-level and transport-nested) plus
    transport rollback counters from a run's telemetry files."""
    from sheeprl_tpu.obs.reader import iter_run_records, key_path

    health, rollback_rounds = [], 0
    for rec in iter_run_records(root_dir):
        if rec.get("health"):
            health.append(rec["health"])
        if key_path(rec, "transport.health"):
            health.append(rec["transport"]["health"])
        rollback_rounds = max(rollback_rounds, key_path(rec, "transport.rollbacks", 0))
    return health, rollback_rounds


def audit_health(health, rollback_rounds, *, budget: int, decoupled: bool) -> list:
    failures = []
    if not health:
        return ["no health telemetry found (sentinel not wired?)"]
    last = max(health, key=lambda h: h.get("updates", 0))
    if last.get("skips", 0) < budget:
        failures.append(f"only {last.get('skips', 0)} skips for a {budget}-skip fault window")
    if last.get("rollbacks", 0) < 1:
        failures.append("no rollback recorded despite a tripped budget")
    if not last.get("last_ok", False):
        failures.append("run ended on an anomalous verdict (no recovery)")
    if decoupled and rollback_rounds < 1:
        failures.append("transport stats did not record the rollback broadcast round")
    return failures


def audit_alerts(leg_root: str, *, expect_rule: str = None) -> list:
    """ISSUE 15: with the live metrics plane armed, an injected fault
    must fire its matching alert rule (a typed ``alert`` fleet event in
    the flight streams AND a ``sheeprl.alert/1`` record in telemetry),
    and a clean leg must fire NOTHING — false alarms train operators to
    ignore the channel."""
    from sheeprl_tpu.obs.reader import read_alerts, read_flight

    flight_alerts = [
        r for r in read_flight(leg_root) if r.get("k") == "event" and r.get("name") == "alert"
    ]
    fired = sorted(
        {
            (r.get("a") or {}).get("rule")
            for r in flight_alerts
            if (r.get("a") or {}).get("state") == "firing"
        }
    )
    failures = []
    if expect_rule is None:
        # slo_* burn rules track latency objectives a loaded CI box can
        # legitimately breach — the zero-false-fires claim is about the
        # fault-shaped rules
        non_slo = [r for r in fired if not r.startswith("slo_")]
        if non_slo:
            failures.append(f"clean leg fired alert rules {non_slo} (expected none)")
        return failures
    if expect_rule not in fired:
        failures.append(f"fault leg never fired rule {expect_rule!r} (fired: {fired})")
    # the same transitions must be queryable post-hoc from the telemetry
    # stream (the sink interleaves alert records)
    tel_rules = {a.get("rule") for a in read_alerts(leg_root) if a.get("state") == "firing"}
    if expect_rule not in tel_rules:
        failures.append(
            f"rule {expect_rule!r} missing from the telemetry alert records ({sorted(tel_rules)})"
        )
    return failures


def _run_health_leg(
    args, faults: str, cli_args: list, leg_root: str, *, decoupled: bool, expect_alert: str = None
) -> list:
    import shutil

    shutil.rmtree(leg_root, ignore_errors=True)
    if faults:
        os.environ["SHEEPRL_FAULTS"] = faults
    from sheeprl_tpu.cli import run

    try:
        run(cli_args)
    finally:
        os.environ.pop("SHEEPRL_FAULTS", None)
    if not faults:
        # clean leg: only the zero-false-fires audit applies
        failures = audit_alerts(leg_root, expect_rule=None)
        print(json.dumps({"leg": os.path.basename(leg_root), "failures": failures}, indent=2))
        return failures
    health, rb_rounds = read_health(leg_root)
    failures = audit_health(health, rb_rounds, budget=3, decoupled=decoupled)
    failures += audit_alerts(leg_root, expect_rule=expect_alert)
    last = max(health, key=lambda h: h.get("updates", 0)) if health else {}
    print(
        json.dumps(
            {
                "leg": os.path.basename(leg_root),
                "skips": last.get("skips"),
                "rollbacks": last.get("rollbacks"),
                "last_rollback": last.get("last_rollback"),
                "ckpt_tags": last.get("ckpt_tags"),
                "transport_rollback_rounds": rb_rounds,
                "failures": failures,
            },
            indent=2,
        )
    )
    return failures


def run_health_mode(args) -> int:
    """ISSUE 7 acceptance: coupled SAC + N=2 decoupled PPO under the
    chosen update fault; both must skip, roll back and finish rc=0."""
    base = args.root_dir
    fault = args.fault
    sentinel = [
        "algo.sentinel.enabled=True",
        "algo.sentinel.warmup=6",
        "algo.sentinel.skip_budget=3",
        "algo.sentinel.good_after=4",
    ]
    common = [
        "env=dummy",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=1",
        "metric.log_level=1",
        "metric.log_every=64",
        # ISSUE 15: the live plane rides every health leg — the injected
        # fault must fire its alert rule, and the clean leg none
        "metric.live=on",
        "metric.tracing=sampled",
        "checkpoint.save_last=True",
        "buffer.memmap=False",
        f"seed={args.seed}",
        "algo.run_test=False",
    ]
    failures = _run_health_leg(
        args,
        f"{fault}:20:3" if fault != "rb_corrupt" else "rb_corrupt:20",
        common
        + sentinel
        + [
            "exp=sac",
            "env.id=dummy_continuous",
            "env.num_envs=4",
            f"metric.logger.root_dir={base}/sac/logs",
            "checkpoint.every=16",
            "algo.total_steps=512",
            "algo.learning_starts=16",
            "algo.per_rank_batch_size=8",
            "algo.hidden_size=8",
            "algo.mlp_keys.encoder=[state]",
            f"root_dir={base}/sac/run",
        ],
        f"{base}/sac",
        decoupled=False,
        expect_alert="sentinel_skip_streak",
    )
    failures += _run_health_leg(
        args,
        f"{fault}:12:3" if fault != "rb_corrupt" else "rb_corrupt:12",
        common
        + sentinel
        + [
            "exp=ppo_decoupled",
            "env.num_envs=4",
            f"metric.logger.root_dir={base}/dec/logs",
            "checkpoint.every=128",
            "algo.total_steps=1024",
            "algo.rollout_steps=4",
            "algo.num_players=2",
            f"algo.decoupled_transport={args.transport}",
            "algo.per_rank_batch_size=4",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.mlp_keys.encoder=[state]",
            f"root_dir={base}/dec/run",
        ],
        f"{base}/dec",
        decoupled=True,
        expect_alert="sentinel_skip_streak",
    )
    # clean leg (no faults): the sentinel stays armed and the live plane
    # must fire ZERO alert rules — the channel stays trustworthy
    failures += _run_health_leg(
        args,
        "",
        common
        + sentinel
        + [
            "exp=sac",
            "env.id=dummy_continuous",
            "env.num_envs=4",
            f"metric.logger.root_dir={base}/clean/logs",
            "checkpoint.every=64",
            "algo.total_steps=256",
            "algo.learning_starts=16",
            "algo.per_rank_batch_size=8",
            "algo.hidden_size=8",
            "algo.mlp_keys.encoder=[state]",
            f"root_dir={base}/clean/run",
        ],
        f"{base}/clean",
        decoupled=False,
    )
    if not args.keep:
        import shutil

        shutil.rmtree(base, ignore_errors=True)
    if failures:
        print("HEALTH CHAOS SOAK FAILED", file=sys.stderr)
        return 1
    print("health chaos soak passed")
    return 0


def read_serve(root_dir: str):
    """Last client-side ``serve`` record and server-side
    ``transport.serve`` record from a run's telemetry files."""
    from sheeprl_tpu.obs.reader import iter_run_records, key_path

    client, server = None, None
    for rec in iter_run_records(root_dir):
        if rec.get("serve"):
            client = rec["serve"]
        if key_path(rec, "transport.serve"):
            server = rec["transport"]["serve"]
    return client, server


def audit_serve(client, server, *, kills: int) -> list:
    failures = []
    if client is None or server is None:
        return ["no serve telemetry found (inference=remote not wired?)"]
    if client.get("breaker_trips", 0) < 1:
        failures.append("breaker never tripped despite the server kill")
    if client.get("local_fallbacks", 0) < 1:
        failures.append("no local fallbacks recorded")
    if client.get("breaker_promotions", 0) < 1:
        failures.append("breaker never re-promoted after the respawn")
    if client.get("breaker") != "closed":
        failures.append(f"run ended with the breaker {client.get('breaker')!r}")
    if client.get("unaccounted", 0) != 0:
        failures.append(f"request-id audit failed: {client.get('unaccounted')} unaccounted")
    if server.get("respawns", 0) < kills:
        failures.append(f"only {server.get('respawns', 0)} respawns for {kills} server kills")
    if not server.get("batches"):
        failures.append("server never dispatched a batch")
    return failures


def run_serve_hot_swap_leg(root: str) -> list:
    """Deterministic sub-leg: a nan-POISONED checkpoint offered for
    hot-swap must be refused (finite spot-check), a good one swapped."""
    import time

    import numpy as np

    from sheeprl_tpu.serve import InferenceServer, agent_params_loader
    from sheeprl_tpu.utils.ckpt_format import save_state

    ckpt_dir = os.path.join(root, "hot_swap", "checkpoint")
    os.makedirs(ckpt_dir, exist_ok=True)
    good = save_state(
        os.path.join(ckpt_dir, "ckpt_100_0.ckpt"),
        {"agent": {"w": np.full((4,), 2.0, np.float32)}},
    )
    time.sleep(0.02)
    save_state(
        os.path.join(ckpt_dir, "ckpt_200_0.ckpt"),
        {"agent": {"w": np.full((4,), np.nan, np.float32)}},  # poisoned, newer
    )
    loader = agent_params_loader("agent")
    srv = InferenceServer(lambda p, o, k: {"actions": o["x"] + p["w"][0]}, {"w": np.zeros(4)})
    srv.watch(os.path.join(root, "hot_swap"), loader, interval_s=1e6)
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore")
        swapped = srv.poll_hot_swap()
    st = srv.stats()["swaps"]
    failures = []
    if st["refused_invalid"] < 1:
        failures.append("nan-poisoned checkpoint was NOT refused")
    if swapped != os.path.abspath(good) or st["applied"] != 1:
        failures.append(f"good checkpoint not swapped in (swapped={swapped}, stats={st})")
    srv.close()
    return failures


def run_serve_mode(args) -> int:
    """ISSUE 8 acceptance soak: a remote-inference N-player run under a
    randomized server-kill (+ tcp net noise) schedule — breakers must
    trip to the local fallback, the supervisor must respawn the server,
    breakers must re-promote, and the request-id audit must be clean —
    plus the poisoned-checkpoint hot-swap refusal sub-leg."""
    import shutil

    rng = random.Random(args.seed)
    kills = max(1, min(args.kills, 2))  # enough batches must fit between kills
    entries = []
    at = 0
    for _ in range(kills):
        at += rng.randrange(30, 80)
        entries.append(f"server_exit:{at}")
    if args.transport == "tcp":
        entries += build_net_noise(rng, args.net_drops, args.net_delays)
    faults = ",".join(entries)
    print(f"serve chaos schedule (seed {args.seed}): SHEEPRL_FAULTS={faults}")

    shutil.rmtree(args.root_dir, ignore_errors=True)
    os.environ["SHEEPRL_FAULTS"] = faults
    from sheeprl_tpu.cli import run

    try:
        run(
            [
                "exp=ppo_decoupled",
                "env=dummy",
                "env.sync_env=True",
                "env.capture_video=False",
                "fabric.accelerator=cpu",
                "fabric.devices=1",
                "metric.log_level=1",
                "metric.log_every=64",
                f"metric.logger.root_dir={args.root_dir}/logs",
                "checkpoint.save_last=True",
                "buffer.memmap=False",
                f"seed={args.seed}",
                "algo.per_rank_batch_size=4",
                "algo.dense_units=8",
                "algo.mlp_layers=1",
                "algo.mlp_keys.encoder=[state]",
                f"algo.total_steps={args.total_steps}",
                f"algo.num_players={args.players}",
                f"algo.decoupled_transport={args.transport}",
                "algo.run_test=False",
                "algo.inference=remote",
                "algo.serve.request_timeout_s=0.25",
                "algo.serve.max_retries=1",
                "algo.serve.breaker_threshold=2",
                "algo.serve.breaker_cooldown_s=1.0",
                "algo.serve.restart_backoff_s=0.2",
                f"algo.serve.restart_budget={kills + 1}",
                f"root_dir={args.root_dir}/run",
                "env.num_envs=4",
                "algo.rollout_steps=4",
                "algo.update_epochs=1",
            ]
        )
    finally:
        os.environ.pop("SHEEPRL_FAULTS", None)

    client, server = read_serve(os.path.join(args.root_dir, "run"))
    failures = audit_serve(client, server, kills=kills)
    failures += run_serve_hot_swap_leg(args.root_dir)
    print(
        json.dumps(
            {
                "client": client,
                "server": {k: v for k, v in (server or {}).items() if k != "batch_hist"},
                "failures": failures,
            },
            indent=2,
        )
    )
    if not args.keep:
        shutil.rmtree(args.root_dir, ignore_errors=True)
    if failures:
        print("SERVE CHAOS SOAK FAILED", file=sys.stderr)
        return 1
    print("serve chaos soak passed")
    return 0


# ------------------------------------------------------------- scale
def read_scale(root_dir: str):
    """Last transport record plus the run's ``autoscale`` flight events
    and player scale-up/retire events (obs/reader.py)."""
    from sheeprl_tpu.obs.reader import iter_run_records, read_flight

    last = None
    for rec in iter_run_records(root_dir):
        if "transport" in rec:
            last = rec["transport"]
    events = [r for r in read_flight(root_dir) if r.get("k") == "event"]
    scaling = [r for r in events if r.get("name") == "autoscale"]
    spawns = [r for r in events if r.get("name") == "player_scale_up"]
    deaths = [r for r in events if r.get("name") == "player_dead"]
    return last, scaling, spawns, deaths


def audit_scale(last, scaling, spawns, deaths, *, players: int, start_players: int) -> list:
    """The elastic-pool convergence audit: the pool must START at the
    autoscaler minimum, GROW on measured pressure (typed ``autoscale``
    flight events, not inference), absorb the mid-scale-up kill, and end
    converged at the configured maximum.  The kill can be healed by
    EITHER actuator — the supervisor's budgeted restart, or (usually,
    since the pool is under sustained pressure and the backoff-delayed
    restart loses the race) the autoscaler's next grow refilling the
    dead slot through the same join machinery; both count, what matters
    is a real death and a reconverged pool."""
    failures = []
    if last is None:
        return ["no transport telemetry found (did the lead die without re-mastering?)"]
    grows = [e for e in scaling if (e.get("a") or {}).get("action") == "grow"]
    need = players - start_players
    if len(grows) < need:
        failures.append(f"only {len(grows)} autoscale grow events for {need} needed slots")
    if len(spawns) < need:
        failures.append(f"only {len(spawns)} player_scale_up events for {need} needed slots")
    first_sizes = [int((e.get("a") or {}).get("size", -1)) for e in grows]
    if grows and start_players not in first_sizes:
        failures.append(
            f"no grow fired from the configured minimum {start_players} "
            f"(sizes seen: {first_sizes}) — pool did not start small"
        )
    pool = last.get("live", 0) + last.get("joining", 0)
    if pool < players:
        failures.append(f"pool never converged: live+joining={pool} < {players}")
    if not deaths:
        failures.append("no player_dead flight event — the scheduled kill never landed")
    restarts = (last.get("supervisor") or {}).get("restarts", 0)
    if restarts < 1 and len(spawns) <= need:
        failures.append(
            f"the kill was never healed: supervisor restarts={restarts} and only "
            f"{len(spawns)} scale-up spawns for {need} vacant slots (no refill)"
        )
    scale_stats = last.get("autoscale") or {}
    if scale_stats.get("grows", 0) < need:
        failures.append(f"telemetry autoscale.grows={scale_stats.get('grows')} < {need}")
    return failures


def run_scale_serve_leg(root: str, seed: int) -> list:
    """Deterministic serving sub-leg: a session swarm against an elastic
    pool whose session cache is DELIBERATELY smaller than the client
    count — every client must survive the resulting ``session_lost``
    storm by reopen-and-replay with zero dropped requests — plus the
    nan-poisoned hot-swap candidate a session server must refuse."""
    import time
    import warnings as _warnings

    import numpy as np

    from scripts.swarm import run_pool_swarm, synthetic_session_parts
    from sheeprl_tpu.serve import SessionInferenceServer, agent_params_loader
    from sheeprl_tpu.utils.ckpt_format import save_state

    failures = []
    clients = 12
    report, stats = run_pool_swarm(
        clients=clients,
        steps=8,
        rows=1,
        think_mean_ms=2.0,
        think_sigma=1.0,
        pool_min=1,
        pool_max=2,
        seed=seed,
        session_capacity=clients // 3,  # thrash: forced LRU evictions
        slo_target_ms=10_000.0,  # latency is not this leg's subject
    )
    d = report.as_dict()
    if d["dropped"] != 0:
        failures.append(f"{d['dropped']} requests dropped under session-cache thrash")
    if d["session_losses"] < 1:
        failures.append("tiny session cache never evicted a live session (session_lost unexercised)")
    if d["session_reopens"] < d["session_losses"]:
        failures.append(
            f"{d['session_losses']} session losses but only {d['session_reopens']} reopens"
        )
    sess = (stats.get("sessions") or {})
    if sess.get("evictions_lru", 0) < 1:
        failures.append(f"no LRU evictions recorded: {sess}")

    # hot-swap refusal on a SESSION server: the newer-but-poisoned
    # candidate is refused, the older finite one applied (PR-8 contract
    # carried over the session decorator)
    params, session_fn, init_fn, _, _ = synthetic_session_parts(seed)
    ckpt_dir = os.path.join(root, "scale_hot_swap", "checkpoint")
    os.makedirs(ckpt_dir, exist_ok=True)
    flat_params = {"agent": {"w": np.full((4,), 2.0, np.float32)}}
    good = save_state(os.path.join(ckpt_dir, "ckpt_100_0.ckpt"), flat_params)
    time.sleep(0.02)
    poisoned = {"agent": {"w": np.full((4,), np.nan, np.float32)}}
    save_state(os.path.join(ckpt_dir, "ckpt_200_0.ckpt"), poisoned)
    srv = SessionInferenceServer(
        None, params, session_policy_fn=session_fn, init_state_fn=init_fn, capacity=8
    )
    srv.watch(os.path.join(root, "scale_hot_swap"), agent_params_loader("agent"), interval_s=1e6)
    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore")
        swapped = srv.poll_hot_swap()
    st = srv.stats()["swaps"]
    if st["refused_invalid"] < 1:
        failures.append("nan-poisoned checkpoint was NOT refused by the session server")
    if swapped != os.path.abspath(good) or st["applied"] != 1:
        failures.append(f"good checkpoint not swapped in (swapped={swapped}, stats={st})")
    srv.close()
    print(
        json.dumps(
            {
                "swarm": {
                    k: d[k]
                    for k in (
                        "dropped",
                        "session_losses",
                        "session_reopens",
                        "actions_per_s",
                        "latency_ms",
                    )
                },
                "sessions": sess,
                "pool_autoscale": stats.get("autoscale"),
                "hot_swap": st,
            },
            indent=2,
        )
    )
    return failures


def run_scale_mode(args) -> int:
    """ISSUE 20 acceptance soak, two legs.  TRAINING: a decoupled run
    whose player pool starts at the autoscaler minimum (1), is grown by
    the telemetry-driven autoscaler under forced gather pressure, loses
    its initially-spawned player a few iterations in — while the pool is
    still scaling up — and must still converge to the configured
    maximum with the kill restarted — all asserted from typed flight
    events and telemetry.  SERVING: the session-cache-thrash swarm plus
    the poisoned hot-swap refusal (:func:`run_scale_serve_leg`)."""
    import shutil

    players = max(2, min(args.players, 3))
    # the kill targets player 0 — the one slot spawned at startup.  The
    # autoscaled slots come up through supervisor._launch, which strips
    # their own player_exit entries (a respawned player must not re-fire
    # its predecessor's kill), so only the initial spawn can die; its
    # 6th own-iteration lands while the pool is still growing
    faults = "player_exit:6:0"
    print(f"scale chaos schedule (seed {args.seed}): SHEEPRL_FAULTS={faults}")

    shutil.rmtree(args.root_dir, ignore_errors=True)
    os.environ["SHEEPRL_FAULTS"] = faults
    from sheeprl_tpu.cli import run

    try:
        run(
            [
                "exp=ppo_decoupled",
                "env=dummy",
                "env.sync_env=True",
                "env.capture_video=False",
                "fabric.accelerator=cpu",
                "fabric.devices=1",
                "metric.log_level=1",
                "metric.log_every=64",
                "metric.tracing=full",  # the audit reads typed flight events
                f"metric.logger.root_dir={args.root_dir}/logs",
                "checkpoint.save_last=True",
                "buffer.memmap=False",
                f"seed={args.seed}",
                "algo.per_rank_batch_size=4",
                "algo.dense_units=8",
                "algo.mlp_layers=1",
                "algo.mlp_keys.encoder=[state]",
                f"algo.total_steps={args.total_steps}",
                f"algo.num_players={players}",
                f"algo.decoupled_transport={args.transport}",
                "algo.run_test=False",
                "algo.supervisor.enabled=True",
                "algo.supervisor.backoff_base=0.1",
                "algo.supervisor.restart_budget=3",
                "algo.autoscaler.enabled=True",
                "algo.autoscaler.min_players=1",
                "algo.autoscaler.up_window_s=0.01",
                "algo.autoscaler.up_cooldown_s=0.1",
                "algo.autoscaler.down_window_s=600",
                # always-pressure: every gather wait >= 0 — the pool must
                # march from 1 to num_players through the real spawn path
                "algo.autoscaler.gather_wait_pressure_s=0.0",
                f"root_dir={args.root_dir}/run",
                "env.num_envs=4",
                "algo.rollout_steps=4",
                "algo.update_epochs=1",
            ]
        )
    finally:
        os.environ.pop("SHEEPRL_FAULTS", None)

    last, scaling, spawns, deaths = read_scale(os.path.join(args.root_dir, "run"))
    failures = audit_scale(last, scaling, spawns, deaths, players=players, start_players=1)
    print(
        json.dumps(
            {
                "pool": {
                    "live": (last or {}).get("live"),
                    "joining": (last or {}).get("joining"),
                    "deaths": (last or {}).get("deaths"),
                    "rejoins": (last or {}).get("rejoins"),
                },
                "autoscale": (last or {}).get("autoscale"),
                "supervisor": (last or {}).get("supervisor"),
                "events": {
                    "autoscale": [e.get("a") for e in scaling],
                    "player_scale_up": len(spawns),
                    "player_dead": len(deaths),
                },
                "failures": failures,
            },
            indent=2,
        )
    )
    failures += run_scale_serve_leg(args.root_dir, args.seed)
    if not args.keep:
        shutil.rmtree(args.root_dir, ignore_errors=True)
    if failures:
        print("SCALE CHAOS SOAK FAILED", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("scale chaos soak passed")
    return 0


# ------------------------------------------------------------- integrity
def _ppo_integrity_args(args, root: str, integrity: str, transport: str, total_steps: int):
    return [
        "exp=ppo_decoupled",
        "env=dummy",
        "env.sync_env=True",
        "env.capture_video=False",
        "fabric.accelerator=cpu",
        "fabric.devices=1",
        "metric.log_level=1",
        "metric.log_every=64",
        f"metric.logger.root_dir={root}/logs",
        "checkpoint.save_last=True",
        "buffer.memmap=False",
        f"seed={args.seed}",
        "algo.per_rank_batch_size=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        f"algo.total_steps={total_steps}",
        "algo.num_players=2",
        f"algo.decoupled_transport={transport}",
        f"algo.transport_integrity={integrity}",
        "algo.run_test=False",
        f"root_dir={root}/run",
        "env.num_envs=4",
        "algo.rollout_steps=4",
        "algo.update_epochs=1",
    ]


def read_integrity(root_dir: str):
    """Last lead ``integrity`` record + the trainer-side counters that
    ride ``transport.integrity`` / ``replay.integrity``, + the last
    ``replay`` record (for the ingest-quarantine leg)."""
    from sheeprl_tpu.obs.reader import iter_run_records

    lead, trainer, replay = {}, {}, {}
    for rec in iter_run_records(root_dir):
        if "integrity" in rec:
            lead = rec["integrity"]
        tr = rec.get("transport") or {}
        if "integrity" in tr:
            trainer = tr["integrity"]
        rp = rec.get("replay") or {}
        if rp:
            replay = rp
            if "integrity" in rp:
                trainer = rp["integrity"]
    return lead, trainer, replay


def audit_integrity(lead, trainer, *, data_flips: int, params_flips: int, transport: str) -> list:
    """Every injected flip must be DETECTED somewhere (data flips at the
    trainer's receive boundary, the lead-directed params flip at the
    lead's), every retransmission must have recovered, and nothing may
    have been silently accepted: detections >= injections, with the
    injection counters themselves riding the same telemetry."""
    failures = []
    if not lead or not trainer:
        return [f"[{transport}] integrity telemetry missing (lead={bool(lead)}, trainer={bool(trainer)})"]
    if trainer.get("frames_corrupt", 0) < data_flips:
        failures.append(
            f"[{transport}] trainer detected {trainer.get('frames_corrupt')} corrupt data "
            f"frames for {data_flips} injected"
        )
    lead_detected = lead.get("frames_corrupt", 0) + lead.get("params_digest_mismatch", 0)
    if lead_detected < params_flips:
        failures.append(
            f"[{transport}] lead detected {lead_detected} corrupt params broadcasts "
            f"for {params_flips} injected"
        )
    for side, rec in (("lead", lead), ("trainer", trainer)):
        if rec.get("retrans_failed", 0):
            failures.append(f"[{transport}] {side} gave up on {rec['retrans_failed']} retransmissions")
    detected = trainer.get("corrupt_detected", 0) + lead.get("corrupt_detected", 0)
    injected = data_flips + params_flips
    silent = injected - detected
    if silent > 0:
        failures.append(f"[{transport}] silent_accepted={silent} (injected {injected}, detected {detected})")
    return failures


def _load_agent_tree(root: str):
    """Newest checkpoint's agent subtree as a flat list of arrays (file
    md5s are useless here: the zip layer stamps wall-clock timestamps)."""
    import numpy as np

    from sheeprl_tpu.utils.ckpt_format import load_state

    ckpts = sorted(
        glob.glob(os.path.join(root, "**", "ckpt_*.ckpt"), recursive=True),
        key=os.path.getmtime,
    )
    if not ckpts:
        return None
    state = load_state(ckpts[-1], select=("agent",))
    import jax

    return [np.asarray(x) for x in jax.tree_util.tree_leaves(state["agent"])]


def run_integrity_mode(args) -> int:
    """ISSUE 10 acceptance soak: on every transport backend, a decoupled
    run under injected ``bit_flip`` faults must DETECT every flip at the
    receive boundary, recover through the retransmit/digest-skip paths,
    and finish rc=0 with the ``integrity`` telemetry proving it.  Plus:
    an rb_insert leg (remote-replay SAC + ``rb_corrupt`` must be
    quarantined at ingest, not silently absorbed) and a paired
    off-vs-crc leg whose final agent params must be BIT-EXACT (crc mode
    perturbs nothing; off mode constructs the pre-integrity objects)."""
    import shutil

    import numpy as np

    from sheeprl_tpu.cli import run

    from sheeprl_tpu.resilience.integrity import reset_integrity_stats

    total_steps = 2560 if args.total_steps == 19200 else args.total_steps
    failures = []
    # one data flip at each player's Nth and Mth shard, one params flip
    # on the trainer's odd-numbered params send — with 2 players the odd
    # sends go to player 0, so the detection lands in the LEAD's
    # telemetry (FanIn.broadcast iterates live pids in order).  The hit
    # counts DIFFER per leg on purpose: the trainer process hosts every
    # leg, and the fault injector is a process-wide singleton keyed on
    # the spec string — an identical spec would stay consumed.
    for idx, transport in enumerate(("queue", "shm", "tcp")):
        faults = f"bit_flip@data:{4 + idx},bit_flip@data:{8 + idx},bit_flip@params:{5 + 2 * idx}"
        root = os.path.join(args.root_dir, transport)
        shutil.rmtree(root, ignore_errors=True)
        print(f"integrity leg [{transport}]: SHEEPRL_FAULTS={faults}")
        reset_integrity_stats()  # trainer-side counters are per-process
        os.environ["SHEEPRL_FAULTS"] = faults
        try:
            run(_ppo_integrity_args(args, root, "digest", transport, total_steps))
        except SystemExit as e:
            if e.code not in (0, None):
                failures.append(f"[{transport}] run exited rc={e.code}")
        finally:
            os.environ.pop("SHEEPRL_FAULTS", None)
        lead, trainer, _ = read_integrity(os.path.join(root, "run"))
        failures += audit_integrity(
            lead, trainer, data_flips=4, params_flips=1, transport=transport
        )
        print(json.dumps({"transport": transport, "lead": lead, "trainer": trainer}))

    # ---- rb_insert leg: rb_corrupt must be detected at ingest
    root = os.path.join(args.root_dir, "rb")
    shutil.rmtree(root, ignore_errors=True)
    print("integrity leg [rb_insert]: SHEEPRL_FAULTS=rb_corrupt:12")
    reset_integrity_stats()
    os.environ["SHEEPRL_FAULTS"] = "rb_corrupt:12"
    try:
        run(
            [
                "exp=sac_decoupled",
                "env=dummy",
                "env.id=dummy_continuous",
                "env.num_envs=2",
                "env.sync_env=True",
                "env.capture_video=False",
                "fabric.accelerator=cpu",
                "fabric.devices=1",
                "metric.log_level=1",
                "metric.log_every=64",
                f"metric.logger.root_dir={root}/logs",
                "checkpoint.save_last=True",
                "buffer.memmap=False",
                "buffer.remote_replay=True",
                "buffer.prioritized=True",
                "algo.num_players=2",
                "algo.per_rank_batch_size=4",
                "algo.dense_units=8",
                "algo.mlp_layers=1",
                "algo.mlp_keys.encoder=[state]",
                "algo.total_steps=640",
                "algo.learning_starts=8",
                "algo.decoupled_transport=queue",
                "algo.transport_integrity=crc",
                "algo.run_test=False",
                f"seed={args.seed}",
                f"root_dir={root}/run",
            ]
        )
    except SystemExit as e:
        if e.code not in (0, None):
            failures.append(f"[rb_insert] run exited rc={e.code}")
    finally:
        os.environ.pop("SHEEPRL_FAULTS", None)
    _, _, replay = read_integrity(os.path.join(root, "run"))
    if replay.get("inserts_quarantined", 0) < 1:
        failures.append(
            f"[rb_insert] rb_corrupt was not quarantined at ingest "
            f"(inserts_quarantined={replay.get('inserts_quarantined')})"
        )
    print(json.dumps({"leg": "rb_insert", "inserts_quarantined": replay.get("inserts_quarantined")}))

    # ---- paired off/crc leg: crc mode must be bit-exact with off mode
    trees = {}
    for integrity in ("off", "crc"):
        root = os.path.join(args.root_dir, f"exact_{integrity}")
        shutil.rmtree(root, ignore_errors=True)
        try:
            run(_ppo_integrity_args(args, root, integrity, "queue", 640))
        except SystemExit as e:
            if e.code not in (0, None):
                failures.append(f"[bit-exact/{integrity}] run exited rc={e.code}")
        trees[integrity] = _load_agent_tree(root)
    if trees.get("off") is None or trees.get("crc") is None:
        failures.append("[bit-exact] a paired run produced no checkpoint")
    elif not all(np.array_equal(a, b) for a, b in zip(trees["off"], trees["crc"])):
        failures.append("[bit-exact] transport_integrity=crc changed the trained agent params")
    else:
        print(json.dumps({"leg": "bit-exact", "leaves": len(trees["off"]), "equal": True}))

    if not args.keep:
        import shutil as _sh

        _sh.rmtree(args.root_dir, ignore_errors=True)
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        print("INTEGRITY CHAOS SOAK FAILED", file=sys.stderr)
        return 1
    print("integrity chaos soak passed")
    return 0


# ------------------------------------------------------------------- ckpt
def _ckpt_cli_code(root: str, mesh_shape: str, seed: int, total_steps: int, resume: bool) -> str:
    """The a2c fsdp leg as a ``python -c`` payload: phase 1 must run in a
    SUBPROCESS because ``ckpt_shard_kill`` SIGKILLs the writing process —
    in-process it would take the soak harness down with it."""
    cli = [
        "exp=a2c",
        "env=dummy",
        "env.sync_env=True",
        "env.capture_video=False",
        "env.num_envs=8",
        "fabric.accelerator=cpu",
        "fabric.devices=8",
        "fabric.strategy=fsdp",
        f"fabric.mesh_shape={mesh_shape}",
        "metric.log_level=1",
        "metric.log_every=64",
        f"metric.logger.root_dir={root}/logs",
        "checkpoint.save_last=True",
        "checkpoint.every=64",
        "checkpoint.sharded=True",
        "buffer.memmap=False",
        f"seed={seed}",
        f"algo.total_steps={total_steps}",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=8",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.run_test=False",
        f"root_dir={root}/run",
    ]
    if resume:
        cli.append("checkpoint.resume_from=auto")
    return "import sys; sys.path.insert(0, {!r})\nfrom sheeprl_tpu.cli import run\nrun({!r})".format(
        _REPO_ROOT, cli
    )


def _scan_dckpts(run_root: str):
    """(complete, partial) sharded-checkpoint directories under a run
    root: complete == the manifest committed (the rename is the atomicity
    point), partial == a writer died before it."""
    dckpts = sorted(glob.glob(os.path.join(run_root, "**", "ckpt_*.dckpt"), recursive=True))
    complete = [d for d in dckpts if os.path.exists(os.path.join(d, "MANIFEST.json"))]
    return complete, [d for d in dckpts if d not in complete]


def read_ckpt_stats(root_dir: str):
    """Every ``ckpt``-keyed telemetry record under a run root (the
    CheckpointManager stats the PR-1 sink interleaves)."""
    from sheeprl_tpu.obs.reader import iter_run_records

    out = []
    for rec in iter_run_records(root_dir):
        if rec.get("ckpt"):
            out.append(rec["ckpt"])
    return out


def run_ckpt_mode(args) -> int:
    """ISSUE 17 acceptance: kill-mid-shard-write must leave a PARTIAL
    directory auto-resume walks past, and the relaunch must reshard the
    last COMPLETE manifest onto a different mesh and finish rc=0."""
    import shutil
    import subprocess

    total_steps = 1280 if args.total_steps == 19200 else args.total_steps
    base = args.root_dir
    shutil.rmtree(base, ignore_errors=True)
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    env.pop("SHEEPRL_FAULTS", None)
    failures = []

    # ---- phase 1: 4x2 mesh, killed during the SECOND checkpoint's shard
    # writes (hits 1-2 are checkpoint #1's two shards; hit 3 is #2's first)
    print("ckpt leg phase 1 (4x2): SHEEPRL_FAULTS=ckpt_shard_kill:3")
    p1 = subprocess.run(
        [sys.executable, "-c", _ckpt_cli_code(base, "4x2", args.seed, total_steps, resume=False)],
        env=dict(env, SHEEPRL_FAULTS="ckpt_shard_kill:3"),
        capture_output=True,
        text=True,
        timeout=600,
    )
    if p1.returncode != -9:
        failures.append(f"phase 1 exited rc={p1.returncode}, expected SIGKILL (-9)")
    complete, partial = _scan_dckpts(os.path.join(base, "run"))
    if not complete:
        failures.append("phase 1 left no COMPLETE manifest before the kill")
    if not partial:
        failures.append("phase 1 left no partial directory (kill landed outside a save?)")
    stats1 = read_ckpt_stats(os.path.join(base, "run"))
    if not any(s.get("sharded") and s.get("shards") == 2 for s in stats1):
        failures.append("phase 1 telemetry never carried 2-shard ckpt stats")
    runs1 = set(glob.glob(os.path.join(base, "run", "*")))

    # ---- phase 2: same root, DIFFERENT mesh (2x4 -> fsdp 2 becomes 4),
    # resume_from=auto must refuse the partial dir and reshard the rest
    print("ckpt leg phase 2 (2x4): checkpoint.resume_from=auto")
    p2 = subprocess.run(
        [sys.executable, "-c", _ckpt_cli_code(base, "2x4", args.seed, total_steps, resume=True)],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    if p2.returncode != 0:
        failures.append(
            f"phase 2 exited rc={p2.returncode}: {p2.stdout[-1500:]}{p2.stderr[-1500:]}"
        )
    expect = f"auto-resume: resuming from {complete[-1]}" if complete else "auto-resume:"
    if expect not in p2.stdout:
        failures.append(f"phase 2 did not resume from the last complete manifest {complete[-1:]}")
    if "skipping corrupt checkpoint" not in (p2.stdout + p2.stderr):
        failures.append("phase 2 never reported walking past the partial directory")

    # the relaunch re-sharded onto the new mesh: its committed manifests
    # carry fsdp_size 4, and its telemetry a 4-shard ckpt section
    complete2, _ = _scan_dckpts(os.path.join(base, "run"))
    new_manifests = [d for d in complete2 if d not in complete]
    if not new_manifests:
        failures.append("phase 2 committed no new manifest")
    else:
        for d in new_manifests:
            with open(os.path.join(d, "MANIFEST.json")) as f:
                doc = json.load(f)
            if int(doc["fsdp_size"]) != 4:
                failures.append(f"{os.path.basename(d)} has fsdp_size {doc['fsdp_size']}, not 4")
        from sheeprl_tpu.utils.ckpt_format import validate_checkpoint

        validate_checkpoint(new_manifests[-1], check_finite=True, check_digests=True)
    runs2 = sorted(set(glob.glob(os.path.join(base, "run", "*"))) - runs1)
    stats2 = []
    for rd in runs2:
        stats2 += read_ckpt_stats(rd)
    if not any(s.get("sharded") for s in stats2):
        failures.append("phase 2 telemetry never carried sharded ckpt stats")

    print(
        json.dumps(
            {
                "phase1_rc": p1.returncode,
                "complete": [os.path.basename(d) for d in complete],
                "partial": [os.path.basename(d) for d in partial],
                "phase2_rc": p2.returncode,
                "new_manifests": [os.path.basename(d) for d in new_manifests],
                "last_ckpt_stats": (stats2 or stats1 or [None])[-1],
                "failures": failures,
            },
            indent=2,
        )
    )
    if not args.keep:
        shutil.rmtree(base, ignore_errors=True)
    if failures:
        print("CKPT CHAOS SOAK FAILED", file=sys.stderr)
        return 1
    print("ckpt chaos soak passed")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--mode",
        default="topology",
        choices=("topology", "health", "serve", "integrity", "ckpt", "scale"),
        help=(
            "topology: kill/rejoin soak (ISSUE 6); health: training sentinel proof "
            "(ISSUE 7); serve: inference-service failure envelope (ISSUE 8); "
            "integrity: bit_flip detection/recovery on all three transports + "
            "rb_insert quarantine + off-vs-crc bit-exactness (ISSUE 10); "
            "ckpt: sharded-checkpoint kill-mid-shard + auto-resume onto a "
            "different mesh (ISSUE 17); scale: elastic-pool autoscaler "
            "convergence under a mid-scale-up kill + session-cache-thrash "
            "swarm + poisoned hot-swap refusal (ISSUE 20)"
        ),
    )
    ap.add_argument(
        "--fault",
        default="nan_inject",
        choices=("nan_inject", "loss_spike", "rb_corrupt"),
        help="health mode: which update fault arms the sentinel's adversary",
    )
    ap.add_argument("--players", type=int, default=4)
    ap.add_argument(
        "--transport",
        default=None,
        choices=("queue", "shm", "tcp"),
        help="default: tcp for topology mode, queue for health mode",
    )
    ap.add_argument("--kills", type=int, default=3)
    ap.add_argument("--net-drops", type=int, default=1)
    ap.add_argument("--net-delays", type=int, default=1)
    ap.add_argument("--total-steps", type=int, default=19200)
    ap.add_argument("--kill-span", type=int, default=60, help="iterations between kills")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--root-dir", default="/tmp/sheeprl_chaos_soak")
    ap.add_argument("--keep", action="store_true", help="keep the run dir for inspection")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.mode == "health":
        if args.root_dir == "/tmp/sheeprl_chaos_soak":
            args.root_dir = "/tmp/sheeprl_chaos_health"
        args.transport = args.transport or "queue"
        return run_health_mode(args)
    if args.mode == "integrity":
        if args.root_dir == "/tmp/sheeprl_chaos_soak":
            args.root_dir = "/tmp/sheeprl_chaos_integrity"
        return run_integrity_mode(args)
    if args.mode == "ckpt":
        if args.root_dir == "/tmp/sheeprl_chaos_soak":
            args.root_dir = "/tmp/sheeprl_chaos_ckpt"
        return run_ckpt_mode(args)
    if args.mode == "scale":
        if args.root_dir == "/tmp/sheeprl_chaos_soak":
            args.root_dir = "/tmp/sheeprl_chaos_scale"
        args.transport = args.transport or "queue"
        if args.players == 4:
            args.players = 3
        if args.total_steps == 19200:
            args.total_steps = 4800
        return run_scale_mode(args)
    if args.mode == "serve":
        if args.root_dir == "/tmp/sheeprl_chaos_soak":
            args.root_dir = "/tmp/sheeprl_chaos_serve"
        args.transport = args.transport or "queue"
        if args.players == 4:
            args.players = 2  # the serve envelope needs breadth, not depth
        if args.total_steps == 19200:
            args.total_steps = 9600
        return run_serve_mode(args)
    args.transport = args.transport or "tcp"

    rng = random.Random(args.seed)
    kill_entries, _ = build_kill_schedule(
        rng, args.players, args.kills, span=args.kill_span
    )
    entries = list(kill_entries)
    if args.transport == "tcp":
        entries += build_net_noise(rng, args.net_drops, args.net_delays)
    faults = ",".join(entries)
    print(f"chaos schedule (seed {args.seed}): SHEEPRL_FAULTS={faults}")

    import shutil

    shutil.rmtree(args.root_dir, ignore_errors=True)
    os.environ["SHEEPRL_FAULTS"] = faults
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from sheeprl_tpu.cli import run

    try:
        run(
            [
                "exp=ppo_decoupled",
                "env=dummy",
                "env.sync_env=True",
                "env.capture_video=False",
                "fabric.accelerator=cpu",
                "fabric.devices=1",
                "metric.log_level=1",
                "metric.log_every=64",
                f"metric.logger.root_dir={args.root_dir}/logs",
                "checkpoint.save_last=True",
                "buffer.memmap=False",
                f"seed={args.seed}",
                "algo.per_rank_batch_size=4",
                "algo.dense_units=8",
                "algo.mlp_layers=1",
                "algo.mlp_keys.encoder=[state]",
                f"algo.total_steps={args.total_steps}",
                f"algo.num_players={args.players}",
                f"algo.decoupled_transport={args.transport}",
                "algo.run_test=False",
                "algo.vtrace.enabled=True",
                "algo.supervisor.enabled=True",
                "algo.supervisor.backoff_base=0.1",
                f"algo.supervisor.restart_budget={args.kills + 2}",
                f"root_dir={args.root_dir}/run",
                "env.num_envs=4",
                "algo.rollout_steps=4",
                "algo.update_epochs=1",
            ]
        )
    finally:
        os.environ.pop("SHEEPRL_FAULTS", None)

    transports, compiles = read_telemetry(os.path.join(args.root_dir, "run"))
    failures = audit(transports, compiles, players=args.players, kills=args.kills)
    last = transports[-1] if transports else {}
    print(
        json.dumps(
            {
                "pool": {
                    "live": last.get("live"),
                    "joining": last.get("joining"),
                    "deaths": last.get("deaths"),
                    "rejoins": last.get("rejoins"),
                },
                "lag_hist": last.get("lag_hist"),
                "supervisor": last.get("supervisor"),
                "trainer_compiles": compiles[-1] if compiles else None,
                "failures": failures,
            },
            indent=2,
        )
    )
    if not args.keep:
        shutil.rmtree(args.root_dir, ignore_errors=True)
    if failures:
        print("CHAOS SOAK FAILED", file=sys.stderr)
        return 1
    print("chaos soak passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
