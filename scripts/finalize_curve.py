"""Shared end-of-chain pipeline: curve + greedy eval -> one JSON artifact.

Every long learning run repeats the same closing steps: stitch the reward
curve across the chain's legs, find the newest checkpoint, sanity-check it
belongs to this chain, greedy-eval it, and fold everything plus run
metadata into a benchmarks/results artifact. This script is that pipeline
once, parameterized — the per-run finalize_*.sh wrappers just supply paths
and metadata (they had drifted as six near-copies before this existed).

Usage:
    python scripts/finalize_curve.py \
        --chain-dir runs/x/chain_r4 --run-dir runs/x \
        --out benchmarks/results/x_curve_r4.json \
        --experiment "..." --protocol "..." \
        [--expl-chain-dir runs/x/chain_expl]  # P2E: exploration-phase trace

Hard-fails (non-zero exit, artifact not written) when the checkpoint is
missing, belongs to a different chain (step gap > --delta-cap), or the
eval produced no ``Test - Reward:`` line — a published artifact always
carries a real greedy-eval number.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scripts.curve_from_logs import stitch  # noqa: E402
from scripts.train_chain import latest_ckpt  # noqa: E402

HARDWARE = "1x TPU v5e (tunneled axon backend) + 1-core CPU host"


def parse_eval_output(eval_txt: str):
    """(last Test-Reward float | None, eval-protocol dict | None).

    The protocol line is emitted by sheeprl_tpu/utils/eval_protocol.py;
    older checkpoints' evals only have the per-episode Test-Reward lines."""
    rewards = re.findall(r"Test - Reward: ([-\d.]+)", eval_txt)
    protocols = re.findall(r"Eval protocol: (\{.*\})", eval_txt)
    protocol = None
    if protocols:
        try:
            protocol = json.loads(protocols[-1])
        except (json.JSONDecodeError, ValueError):
            # a truncated/garbled protocol line (killed eval, interleaved
            # writes) must not crash the whole finalize — fall back to the
            # legacy Test-Reward path with a visible warning
            print(
                "WARNING: 'Eval protocol:' line is not valid JSON (truncated "
                "eval output?); falling back to the legacy 'Test - Reward:' "
                "number only.",
                file=sys.stderr,
            )
    return (float(rewards[-1]) if rewards else None, protocol)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--chain-dir", required=True)
    ap.add_argument("--run-dir", required=True, help="checkpoint search root")
    ap.add_argument("--out", required=True)
    ap.add_argument("--experiment", required=True, help="artifact 'experiment' field")
    ap.add_argument("--protocol", default=None, help="artifact 'protocol' field")
    ap.add_argument("--hardware", default=HARDWARE)
    ap.add_argument("--extra-log", action="append", default=[])
    ap.add_argument("--delta-cap", type=int, default=26000,
                    help="max |ckpt step - curve final step| before refusing")
    ap.add_argument("--eval-timeout", type=int, default=4800,
                    help="seconds; the default covers the 10-episode protocol "
                         "(5 greedy + 5 sampled) at ~8 min/episode")
    ap.add_argument("--eval-log", default=None,
                    help="persist the eval's full output here "
                         "(default: /tmp/<artifact-stem>_eval.log)")
    ap.add_argument("--expl-chain-dir", default=None,
                    help="optional exploration-phase chain (P2E): its stitched "
                         "task-reward trace is embedded as exploration_phase")
    ap.add_argument("--smooth", type=int, default=5,
                    help="reward-binning window passed to stitch()")
    args = ap.parse_args()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    artifact = stitch(args.chain_dir, args.extra_log, smooth=args.smooth)
    if not artifact["curve"]:
        print(f"ERROR: no reward points stitched from {args.chain_dir}", file=sys.stderr)
        return 1

    ckpt_step, ckpt = latest_ckpt(args.run_dir)
    if not ckpt:
        print(f"ERROR: no checkpoint found under {args.run_dir}", file=sys.stderr)
        return 1
    delta = abs(ckpt_step - artifact["final_step"])
    if delta > args.delta_cap:
        print(
            f"ERROR: newest ckpt step {ckpt_step} is {delta} steps from the "
            f"curve's final step {artifact['final_step']} — wrong chain's "
            "checkpoint?",
            file=sys.stderr,
        )
        return 1

    print(f"evaluating {ckpt}")
    eval_log = args.eval_log or os.path.join(
        "/tmp", os.path.splitext(os.path.basename(args.out))[0] + "_eval.log")
    env = {**os.environ, "MUJOCO_GL": os.environ.get("MUJOCO_GL", "egl")}
    # stream to a file (not PIPE): a hung/killed eval still leaves a
    # debuggable log on disk, and the artifact never publishes without it
    with open(eval_log, "w") as lf:
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(repo, "sheeprl_eval.py"),
                 f"checkpoint_path={ckpt}", "env.capture_video=False"],
                stdout=lf, stderr=lf, timeout=args.eval_timeout, cwd=repo, env=env,
            )
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            rc = "timeout"
    with open(eval_log, errors="replace") as f:
        eval_txt = f.read()
    tail = "\n".join(eval_txt.strip().splitlines()[-15:])
    if rc != 0:
        print(
            f"ERROR: eval exited with {rc} — refusing to publish the artifact "
            f"from a failed eval run. Full log: {eval_log}; tail:\n{tail}",
            file=sys.stderr,
        )
        return 1
    headline, protocol = parse_eval_output(eval_txt)
    if headline is None:
        print(
            "ERROR: no 'Test - Reward:' line in the eval output — eval failed "
            "or its output format drifted; refusing to publish the artifact "
            f"without the greedy-eval number. Full log: {eval_log}; tail:\n{tail}",
            file=sys.stderr,
        )
        return 1
    print(f"Test - Reward: {headline}")

    # multi-episode protocol summary (greedy + sampled per-episode lists);
    # the final 'Test - Reward:' line is the protocol's greedy median, so
    # the legacy field below stays a robust statistic either way
    if protocol is not None:
        artifact["eval_protocol"] = protocol
    else:
        print(
            "WARNING: no 'Eval protocol:' line — single-episode eval output "
            "(pre-protocol checkpoint format?); publishing the last "
            "'Test - Reward:' as the only eval number.",
            file=sys.stderr,
        )
    artifact["greedy_eval_reward_at_final_ckpt"] = headline
    artifact["eval_ckpt_step"] = ckpt_step
    artifact["experiment"] = args.experiment
    artifact["hardware"] = args.hardware
    if args.protocol:
        artifact["protocol"] = args.protocol

    if args.expl_chain_dir:
        expl = stitch(args.expl_chain_dir, smooth=args.smooth)
        if not expl["curve"]:
            print(
                f"ERROR: --expl-chain-dir {args.expl_chain_dir} stitched to an "
                "empty curve — wrong chain dir layout? (expects leg_*.log + "
                "status.jsonl, as written by scripts/train_chain.py)",
                file=sys.stderr,
            )
            return 1
        vals = [p["reward_mean"] for p in expl["curve"]]
        artifact["exploration_phase"] = {
            "note": (
                "task-reward trace of the exploration phase (the policy "
                "optimizes ensemble disagreement, not task reward — near-zero "
                "rewards here are the point on a sparse task)"
            ),
            "summary": {
                "episodes_binned": expl["n_points"],
                "reward_mean": round(sum(vals) / len(vals), 3) if vals else None,
                "reward_max": max(p["reward_max"] for p in expl["curve"]) if expl["curve"] else None,
                "final_step": expl["final_step"],
            },
            "curve": expl["curve"],
        }

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
    print(json.dumps({k: artifact.get(k) for k in (
        "final_step", "final_reward_mean", "best_reward_mean",
        "greedy_eval_reward_at_final_ckpt")}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
