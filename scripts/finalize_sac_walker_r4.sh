#!/bin/bash
# End-of-chain pipeline for the round-4 SAC walker-walk run (BASELINE.md
# driver config #2: SAC, DMC walker-walk, vector obs, numpy ReplayBuffer).
# Stitches the reward curve across chain legs, greedy-evals the newest
# checkpoint, and folds the eval into the curve artifact. Run AFTER the
# chain has stopped.
# FROZEN RECORD: this script already produced its committed artifact and
# is kept as the exact pipeline that made it. New runs should use the
# shared scripts/finalize_curve.py instead (see finalize_dv2_walker_r4.sh
# for the wrapper pattern).
set -e -o pipefail
cd /root/repo
OUT=benchmarks/results/sac_walker_walk_curve_r4.json

# leg 0 resumed from the 48K-step smoke run of the SAME config on the SAME
# machine (runs/sac_walker/smoke); its log is stitched in as the curve's
# 0-48K prefix so the artifact covers the whole from-scratch trajectory.
python scripts/curve_from_logs.py \
  --chain-dir runs/sac_walker/chain_r4 \
  --extra-log runs/sac_walker/smoke_0_48k.log \
  --out "$OUT"

CKPT=$(python - <<'EOF'
from scripts.train_chain import latest_ckpt
step, ckpt = latest_ckpt("runs/sac_walker")
print(ckpt)
EOF
)
if [ -z "$CKPT" ] || [ "$CKPT" = "None" ]; then
  echo "ERROR: no checkpoint found under runs/sac_walker" >&2
  exit 1
fi
CKPT_STEP=$(basename "$CKPT" | sed -E 's/ckpt_([0-9]+)_.*/\1/')
FINAL_STEP=$(python -c "import json,sys; print(json.load(open('$OUT'))['final_step'])")
# threshold covers one checkpoint cadence even at the yaml default
# (checkpoint.every: 25000; the chain overrides to 4000) — the guard is for
# wrong-chain checkpoints, which would be off by hundreds of thousands
DELTA=$((CKPT_STEP - FINAL_STEP)); DELTA=${DELTA#-}
if [ "$DELTA" -gt 26000 ]; then
  echo "ERROR: newest ckpt step $CKPT_STEP is $DELTA steps from the curve's final step $FINAL_STEP — wrong chain's checkpoint?" >&2
  exit 1
fi
echo "evaluating $CKPT"
MUJOCO_GL=egl timeout 1200 python sheeprl_eval.py "checkpoint_path=$CKPT" \
  env.capture_video=False 2>&1 | tee /tmp/sac_walker_eval_r4.log | tail -3

python - "$OUT" "$CKPT_STEP" <<'EOF'
import json, re, sys
out, ckpt_step = sys.argv[1], int(sys.argv[2])
d = json.load(open(out))
txt = open("/tmp/sac_walker_eval_r4.log").read()
m = re.findall(r"Test - Reward: ([-\d.]+)", txt)
d["greedy_eval_reward_at_final_ckpt"] = float(m[-1]) if m else None
d["eval_ckpt_step"] = ckpt_step
d["experiment"] = ("sac_dmc_walker_walk (BASELINE.md config #2: SAC, dm_control "
                   "walker-walk, 24-dim proprio vector obs, numpy ReplayBuffer + "
                   "HBM device cache, 4 envs, batch 256, replay_ratio 1.0, "
                   "dispatch_batch 64)")
d["hardware"] = "1x TPU v5e (tunneled axon backend) + 1-core CPU host"
d["protocol"] = ("trained FROM SCRATCH this round: 0-48K steps as a single run, "
                 "then scripts/train_chain.py checkpoint-resume legs to 500K "
                 "(RSS-capped); curve = episode-end rewards binned from stdout; "
                 "typical SAC asymptote on walker-walk is ~900-970")
json.dump(d, open(out, "w"), indent=2)
print(json.dumps({k: d.get(k) for k in ("final_step", "final_reward_mean", "best_reward_mean", "greedy_eval_reward_at_final_ckpt")}))
EOF
