"""Standalone policy serving: point the InferenceServer at a checkpoint.

The same serving plane the decoupled loops embed (serve/service.py), run
as a process of its own for offline/production serving: load a trained
checkpoint, open a TCP listener (or in-process channels with
``--selftest``), and answer observation frames with actions — with
deadline batching, bucketed XLA traces, request-id dedupe, graceful
SIGTERM drain, and (``--watch``) validated hot checkpoint swap: newly
good-tagged checkpoints under the run root are spot-checked and swapped
in between batches; quarantined/corrupt candidates are refused and
logged.

Serve the newest checkpoint of a run over tcp::

    python scripts/serve_policy.py --checkpoint logs/.../ckpt_1024_0.ckpt \
        --host 0.0.0.0 --port 7501 --watch

Env workers connect with the client half::

    from sheeprl_tpu.parallel.transport import TcpChannel
    from sheeprl_tpu.serve import InferenceClient
    chan = TcpChannel(address=(host, 7501), player_id=0, reconnect=True)
    client = InferenceClient(chan, 0)
    out, src = client.infer([("state", obs)], rows)

``--selftest N`` instead drives the server with N in-process clients on
random observations and prints the latency/batching stats as JSON — the
quickest way to see the serving envelope working without a second
process.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

# runnable as `python scripts/serve_policy.py`
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _load_run_cfg(ckpt_path: str):
    """The run config saved next to the checkpoint (same resolution as
    the evaluation app: <run>/config.yaml two levels up, falling back to
    the checkpoint's own directory)."""
    from sheeprl_tpu.config import dotdict
    from sheeprl_tpu.config.compose import yaml_load

    ckpt_dir = os.path.dirname(os.path.dirname(os.path.abspath(ckpt_path)))
    cfg_path = os.path.join(ckpt_dir, "config.yaml")
    if not os.path.exists(cfg_path):
        cfg_path = os.path.join(os.path.dirname(os.path.abspath(ckpt_path)), "config.yaml")
    if not os.path.exists(cfg_path):
        raise RuntimeError(f"Cannot find the run config next to the checkpoint: {cfg_path}")
    with open(cfg_path) as f:
        return dotdict(yaml_load(f.read()))


def build_server(
    ckpt_path: str,
    *,
    greedy: bool = True,
    deadline_ms: float = 5.0,
    max_batch: int = 64,
    session_capacity: int = 1024,
    session_ttl_s: float = 300.0,
):
    """Checkpoint -> a ready (not yet started) server + the obs keys its
    requests must carry.  Stateless families (PPO/SAC) get the PR-8
    InferenceServer; recurrent families (recurrent PPO, Dreamer v3) get
    the SESSION tier — clients must speak the session protocol
    (SessionClient.step), because a recurrent policy served statelessly
    is meaningless."""
    import gymnasium as gym

    from sheeprl_tpu.parallel.mesh import MeshRuntime
    from sheeprl_tpu.serve import (
        agent_params_loader,
        make_dreamer_session_fns,
        make_ppo_policy_fn,
        make_recurrent_ppo_session_fns,
        make_sac_policy_fn,
    )
    from sheeprl_tpu.serve import build_server as _make_server
    from sheeprl_tpu.utils.env import make_env

    cfg = _load_run_cfg(ckpt_path)
    algo = str(cfg.algo.name)
    if algo.startswith("ppo_recurrent"):
        family = "ppo_recurrent"
    elif algo.startswith("dreamer_v3"):
        family = "dreamer_v3"
    elif algo.startswith(("ppo", "a2c")):
        family = "ppo"
    elif algo.startswith(("sac", "droq")):
        family = "sac"
    else:
        raise ValueError(
            f"serve_policy supports the PPO/SAC/recurrent-PPO/Dreamer-v3 families, got algo={algo!r}"
        )

    runtime = MeshRuntime(devices=1, accelerator="cpu", precision=cfg.fabric.get("precision", "32-true"))
    runtime.launch()
    cfg.env.capture_video = False
    env = make_env(cfg, int(cfg.get("seed", 0)), 0, None, "serve", vector_env_idx=0)()
    observation_space, action_space = env.observation_space, env.action_space
    env.close()

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )

    policy_fn = session_policy_fn = init_state_fn = None
    if family == "ppo":
        from sheeprl_tpu.algos.ppo.agent import build_agent

        loader = agent_params_loader("agent")
        params = loader(ckpt_path)
        module, params = build_agent(runtime, actions_dim, is_continuous, cfg, observation_space, params)
        policy_fn = make_ppo_policy_fn(module, cfg.algo.cnn_keys.encoder, greedy=greedy)
        obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)
    elif family == "ppo_recurrent":
        from sheeprl_tpu.algos.ppo_recurrent.agent import build_agent

        loader = agent_params_loader("agent")
        params = loader(ckpt_path)
        module, params = build_agent(runtime, actions_dim, is_continuous, cfg, observation_space, params)
        session_policy_fn, init_state_fn = make_recurrent_ppo_session_fns(module, greedy=greedy)
        obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)
    elif family == "dreamer_v3":
        from sheeprl_tpu.algos.dreamer_v3.agent import build_agent

        from sheeprl_tpu.utils.callback import load_checkpoint

        def loader(path: str):
            # Dreamer checkpoints carry top-level world_model/actor trees;
            # serving needs exactly the player's composite
            state = load_checkpoint(path)
            return {"world_model": state["world_model"], "actor": state["actor"]}

        state = loader(ckpt_path)
        world_model, actor_mod, _, params = build_agent(
            runtime, actions_dim, is_continuous, cfg, observation_space,
            state["world_model"], state["actor"],
        )
        params = {"world_model": params["world_model"], "actor": params["actor"]}
        wm_cfg = cfg.algo.world_model
        session_policy_fn, init_state_fn = make_dreamer_session_fns(
            world_model,
            actor_mod,
            actions_dim=actions_dim,
            stochastic_size=int(wm_cfg.stochastic_size),
            discrete_size=int(wm_cfg.discrete_size),
            recurrent_state_size=int(wm_cfg.recurrent_model.recurrent_state_size),
            decoupled_rssm=bool(wm_cfg.get("decoupled_rssm", False)),
            greedy=greedy,
        )
        obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)
    else:
        from sheeprl_tpu.algos.sac.agent import build_agent

        # decoupled SAC checkpoints carry the full agent tree; serving
        # needs only the actor subtree
        loader = agent_params_loader("agent")
        state_agent = loader(ckpt_path)
        actor, _, params, _ = build_agent(runtime, cfg, observation_space, action_space, state_agent)
        params = params["actor"]
        policy_fn = make_sac_policy_fn(actor, cfg.algo.mlp_keys.encoder, greedy=greedy)
        loader = agent_params_loader("agent/actor")
        obs_keys = list(cfg.algo.mlp_keys.encoder)

    server = _make_server(
        policy_fn,
        params,
        session={
            "enabled": session_policy_fn is not None,
            "capacity": int(session_capacity),
            "idle_ttl_s": float(session_ttl_s),
        },
        session_policy_fn=session_policy_fn,
        init_state_fn=init_state_fn,
        deadline_ms=deadline_ms,
        max_batch=max_batch,
        seed=int(cfg.get("seed", 0)),
        name=algo,
    )
    server.swap_params(params, source=os.path.abspath(ckpt_path))
    return server, loader, obs_keys, observation_space


def run_selftest(server, obs_keys, observation_space, n_clients: int, n_requests: int) -> int:
    """Drive the server with in-process clients over queue channels."""
    import multiprocessing as mp
    import threading

    import numpy as np

    from sheeprl_tpu.parallel.transport import make_transport
    from sheeprl_tpu.serve import InferenceClient

    from sheeprl_tpu.serve import SessionClient, SessionInferenceServer

    sessions = isinstance(server, SessionInferenceServer)
    ctx = mp.get_context("spawn")
    hub, specs = make_transport(ctx, "queue", n_clients, window=4, min_bytes=0)
    make_client = (lambda ch, i: SessionClient(ch, i, seed=i)) if sessions else InferenceClient
    clients = [make_client(specs[i].player_channel(), i) for i in range(n_clients)]
    for i in range(n_clients):
        server.attach(i, hub.channel(i, timeout=5))
    server.start()

    failures = []

    def drive(cid: int) -> None:
        rng = np.random.default_rng(cid)
        for _ in range(n_requests):
            obs = {
                k: rng.normal(size=(1,) + tuple(observation_space[k].shape)).astype(np.float32)
                for k in obs_keys
            }
            arrays = [(k, v) for k, v in obs.items()]
            if sessions:
                out, src = clients[cid].step(arrays, 1)
            else:
                out, src = clients[cid].infer(arrays, 1)
            if src != "remote" or out is None:
                failures.append(cid)
                return
        if sessions:
            clients[cid].close_session()

    threads = [threading.Thread(target=drive, args=(i,)) for i in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    stats = server.stats()
    stats["selftest"] = {
        "clients": n_clients,
        "requests_per_client": n_requests,
        "wall_s": round(wall, 3),
        "actions_per_s": round(n_clients * n_requests / wall, 1),
        "failures": len(failures),
    }
    print(json.dumps(stats, indent=2))
    server.close()
    hub.close()
    return 1 if failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--checkpoint", required=True, help="ckpt_*.ckpt to serve")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=7501)
    ap.add_argument("--deadline-ms", type=float, default=5.0)
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--sample", action="store_true", help="sample actions instead of greedy")
    ap.add_argument("--session-capacity", type=int, default=1024,
                    help="session-cache LRU bound (recurrent families)")
    ap.add_argument("--session-ttl", type=float, default=300.0,
                    help="session idle TTL in seconds (recurrent families)")
    ap.add_argument(
        "--watch", action="store_true",
        help="hot-swap: watch the run root for newly good-tagged checkpoints",
    )
    ap.add_argument("--watch-interval", type=float, default=2.0)
    ap.add_argument("--stats-every", type=float, default=10.0, help="stats JSON line cadence (s)")
    ap.add_argument("--selftest", type=int, default=0, metavar="N", help="drive with N in-process clients and exit")
    ap.add_argument("--selftest-requests", type=int, default=64)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    server, loader, obs_keys, obs_space = build_server(
        args.checkpoint,
        greedy=not args.sample,
        deadline_ms=args.deadline_ms,
        max_batch=args.max_batch,
        session_capacity=args.session_capacity,
        session_ttl_s=args.session_ttl,
    )
    if args.watch:
        run_root = os.path.dirname(os.path.dirname(os.path.abspath(args.checkpoint)))
        server.watch(run_root, loader, interval_s=args.watch_interval)

    if args.selftest > 0:
        return run_selftest(server, obs_keys, obs_space, args.selftest, args.selftest_requests)

    from sheeprl_tpu.parallel.transport import TcpListener

    listener = TcpListener(args.host, args.port, window=8)
    print(f"serving {args.checkpoint} on {listener.address} (obs keys: {obs_keys})", flush=True)

    # adopt clients as they dial in (the hello frame carries their id)
    import threading

    def adopt_loop() -> None:
        seen = set()
        while server.alive or not server._stop.is_set():
            with listener._cond:
                pids = list(listener._channels)
            for pid in pids:
                if pid not in seen:
                    seen.add(pid)
                    server.attach(pid, listener._channels[pid])
                    print(f"client {pid} connected", flush=True)
            time.sleep(0.2)

    threading.Thread(target=adopt_loop, daemon=True).start()
    server.start()

    # SIGTERM/SIGINT: graceful drain — answer pending, send stop frames
    def on_term(signum, frame):
        print("drain requested", flush=True)
        server.request_drain()

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)

    last = 0.0
    while server._thread is not None and server._thread.is_alive():
        time.sleep(0.2)
        if time.monotonic() - last >= args.stats_every:
            last = time.monotonic()
            print(json.dumps(server.stats()), flush=True)
    print(json.dumps(server.stats()), flush=True)
    server.close()
    listener.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
