"""Cross-round benchmark trend table — where is the perf line moving?

Reads every committed ``BENCH_r*.json`` (the per-round benchmark capsules
whose ``tail`` holds bench.py's JSON metric lines) and prints one row per
headline metric with its value across rounds and a direction mark for the
last hop: ``+`` improved, ``-`` regressed, ``=`` flat (<1% move), ``?``
for metrics whose unit has no better-direction (same unit table as
bench.py's perf gate).  A second section lists the one-off committed
result files under ``benchmarks/results/*.json`` (proof-run artifacts
like the round-16 superbench) with their top-level scalars.

Pure stdlib on purpose: bench.py's parent process shells out to this as
its epilogue (stderr only — stdout there is reserved for metric lines),
and it must stay importable without jax.

Usage: python scripts/bench_trend.py [--repo PATH] [--metric SUBSTR]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_LOWER_IS_BETTER_UNITS = ("s", "ms")
_HIGHER_IS_BETTER_UNITS = ("frames/s", "x", "steps/s")
_FLAT_PCT = 1.0


def load_rounds(repo: str):
    """``[(round_name, {metric: {"value": .., "unit": ..}}), ...]`` oldest
    first, parsed the same way as bench.py's gate (last occurrence of a
    metric in the tail wins)."""
    paths = sorted(
        glob.glob(os.path.join(repo, "BENCH_r*.json")),
        key=lambda p: int(re.search(r"r(\d+)", os.path.basename(p)).group(1)),
    )
    rounds = []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        metrics = {}
        for line in str(doc.get("tail", "")).splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if "metric" in rec and isinstance(rec.get("value"), (int, float)):
                metrics[rec["metric"]] = {"value": float(rec["value"]), "unit": rec.get("unit")}
        rounds.append((os.path.basename(path).replace("BENCH_", "").replace(".json", ""), metrics))
    return rounds


def _direction(unit: str):
    if unit in _LOWER_IS_BETTER_UNITS:
        return -1
    if unit in _HIGHER_IS_BETTER_UNITS:
        return +1
    return 0


def _mark(prev, cur, unit):
    if prev is None or cur is None or not prev:
        return " "
    d = _direction(unit or "")
    change_pct = (cur / prev - 1.0) * 100.0
    if d == 0:
        return "?"
    if abs(change_pct) < _FLAT_PCT:
        return "="
    return "+" if (change_pct > 0) == (d > 0) else "-"


def _fmt(v):
    if v is None:
        return "-"
    return f"{v:g}" if abs(v) < 1e5 else f"{v:.3g}"


def trend_table(rounds, metric_filter: str = "") -> str:
    if not rounds:
        return "no committed BENCH_r*.json rounds found\n"
    names = []
    for _, metrics in rounds:
        for name in metrics:
            if name not in names:
                names.append(name)
    if metric_filter:
        names = [n for n in names if metric_filter in n]
    heads = [r for r, _ in rounds]
    width = max([len(n) for n in names] + [6]) if names else 6
    out = ["bench trend (last-hop mark: + better, - worse, = flat, ? no direction)"]
    out.append("  " + "metric".ljust(width) + "  unit      " + "  ".join(h.rjust(9) for h in heads))
    for name in names:
        vals = [m.get(name, {}).get("value") for _, m in rounds]
        unit = next((m[name].get("unit") for _, m in rounds if name in m), "") or ""
        prev = next((v for v in reversed(vals[:-1]) if v is not None), None)
        mark = _mark(prev, vals[-1], unit) if len(vals) > 1 else " "
        cells = "  ".join(_fmt(v).rjust(9) for v in vals)
        out.append(f"{mark} {name.ljust(width)}  {unit.ljust(8)}  {cells}")
    return "\n".join(out) + "\n"


def results_table(repo: str) -> str:
    paths = sorted(glob.glob(os.path.join(repo, "benchmarks", "results", "*.json")))
    rows = []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict):
            continue
        scalars = {k: v for k, v in doc.items() if isinstance(v, (int, float)) and not isinstance(v, bool)}
        if not scalars:
            continue
        head = ", ".join(f"{k}={_fmt(float(v))}" for k, v in list(scalars.items())[:4])
        rows.append(f"  {os.path.basename(path)}: {head}")
    if not rows:
        return ""
    return "committed one-off results (benchmarks/results/):\n" + "\n".join(rows) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--repo", default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--metric", default="", help="substring filter on metric names")
    args = ap.parse_args(argv)
    sys.stdout.write(trend_table(load_rounds(args.repo), args.metric))
    extra = results_table(args.repo)
    if extra:
        sys.stdout.write("\n" + extra)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
