#!/bin/bash
# End-of-chain pipeline for the round-4 DreamerV2 walker-walk run.
# Run AFTER the chain has stopped. Thin wrapper over finalize_curve.py
# (the shared stitch + sanity-check + greedy-eval pipeline).
set -e -o pipefail
cd /root/repo
exec python scripts/finalize_curve.py \
  --chain-dir runs/dv2_walker/chain_r4 \
  --run-dir runs/dv2_walker \
  --out benchmarks/results/dv2_walker_walk_curve_r4.json \
  --experiment "dreamer_v2_dmc_walker_walk (DreamerV2, dm_control walker-walk from 64x64 pixels, paper dmc_vision recipe: deter/hidden 200, dynamics-backprop actor, action_repeat 2, replay_ratio 0.2, 8 async envs, HBM replay cache at 12500 frames/env)" \
  --protocol "trained FROM SCRATCH this round via scripts/train_chain.py checkpoint-resume legs; curve = episode-end rewards binned from stdout; first learning-evidence artifact for the DreamerV2 family (DV3 curves: walker 742.8@100K r3, cartpole 865.5@204K r4)"
