#!/bin/bash
# End-of-chain pipeline for the round-4 DreamerV2 walker-walk run: stitch
# the reward curve across chain legs, greedy-eval the newest checkpoint,
# fold the eval into the curve artifact. Run AFTER the chain has stopped.
set -e -o pipefail
cd /root/repo
OUT=benchmarks/results/dv2_walker_walk_curve_r4.json

python scripts/curve_from_logs.py \
  --chain-dir runs/dv2_walker/chain_r4 \
  --out "$OUT"

CKPT=$(python - <<'EOF'
from scripts.train_chain import latest_ckpt
step, ckpt = latest_ckpt("runs/dv2_walker")
print(ckpt)
EOF
)
if [ -z "$CKPT" ] || [ "$CKPT" = "None" ]; then
  echo "ERROR: no checkpoint found under runs/dv2_walker" >&2
  exit 1
fi
CKPT_STEP=$(basename "$CKPT" | sed -E 's/ckpt_([0-9]+)_.*/\1/')
FINAL_STEP=$(python -c "import json,sys; print(json.load(open('$OUT'))['final_step'])")
DELTA=$((CKPT_STEP - FINAL_STEP)); DELTA=${DELTA#-}
if [ "$DELTA" -gt 26000 ]; then
  echo "ERROR: newest ckpt step $CKPT_STEP is $DELTA steps from the curve's final step $FINAL_STEP — wrong chain's checkpoint?" >&2
  exit 1
fi
echo "evaluating $CKPT"
MUJOCO_GL=egl timeout 1200 python sheeprl_eval.py "checkpoint_path=$CKPT" \
  env.capture_video=False 2>&1 | tee /tmp/dv2_walker_eval_r4.log | tail -3

python - "$OUT" "$CKPT_STEP" <<'EOF'
import json, re, sys
out, ckpt_step = sys.argv[1], int(sys.argv[2])
d = json.load(open(out))
txt = open("/tmp/dv2_walker_eval_r4.log").read()
m = re.findall(r"Test - Reward: ([-\d.]+)", txt)
if not m:
    sys.exit("ERROR: no 'Test - Reward:' line in the eval log — eval failed or "
             "its output format drifted; refusing to publish the artifact "
             "without the greedy-eval number")
d["greedy_eval_reward_at_final_ckpt"] = float(m[-1])
d["eval_ckpt_step"] = ckpt_step
d["experiment"] = ("dreamer_v2_dmc_walker_walk (DreamerV2, dm_control walker-walk "
                   "from 64x64 pixels, paper dmc_vision recipe: deter/hidden 200, "
                   "dynamics-backprop actor, action_repeat 2, replay_ratio 0.2, "
                   "8 async envs, HBM replay cache at 12500 frames/env)")
d["hardware"] = "1x TPU v5e (tunneled axon backend) + 1-core CPU host"
d["protocol"] = ("trained FROM SCRATCH this round via scripts/train_chain.py "
                 "checkpoint-resume legs; curve = episode-end rewards binned from "
                 "stdout; first learning-evidence artifact for the DreamerV2 family "
                 "(DV3 curves: walker 742.8@100K r3, cartpole 865.5@204K r4)")
json.dump(d, open(out, "w"), indent=2)
print(json.dumps({k: d.get(k) for k in ("final_step", "final_reward_mean", "best_reward_mean", "greedy_eval_reward_at_final_ckpt")}))
EOF
