"""Automated checkpoint-resume chain for long on-chip training runs.

The tunneled-TPU client in this environment leaks native memory under
sustained train dispatch (RSS grows while ``jax.live_arrays()`` stays
flat — see README "known issues"), which caps any single process at a few
hours.  This runner turns the manual mitigation into an unattended chain:

    launch leg -> watch RSS / wall-clock -> stop leg at a checkpoint
    boundary -> relaunch with ``checkpoint.resume_from=<latest>`` -> ...

until a target policy step, an absolute deadline, or a failure cap is
reached.  Each leg's stdout goes to ``<chain-dir>/leg_NNN.log`` so reward
curves can be stitched across legs afterwards (``scripts/curve_from_logs.py``).

Example (the round-3 walker-walk run):

    python scripts/train_chain.py \
      --run-dir runs/dv3_walker --chain-dir runs/dv3_walker/chain_r3 \
      --target-step 100000 --deadline-ts 1785489000 \
      --leg-seconds 7200 --max-rss-gb 85 \
      -- exp=dreamer_v3_dmc_walker_walk env.num_envs=8 \
         algo.replay_ratio=0.3 buffer.size=100000 buffer.memmap=False \
         checkpoint.every=4000 checkpoint.keep_last=3 \
         root_dir=/root/repo/runs/dv3_walker

Stopping policy: a leg is SIGTERM'd (then SIGKILL'd after a grace period)
when it exceeds the per-leg wall-clock or RSS cap; progress since the
last checkpoint is lost, so ``checkpoint.every`` should be small relative
to the leg length.  The chain stops when the newest checkpoint reaches
``--target-step``, the deadline passes, or ``--max-failures`` legs in a
row exit without writing a new checkpoint.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import signal
import subprocess
import sys
import time


def latest_ckpt(run_dir: str):
    """Newest checkpoint by (step, mtime) under run_dir, or None."""
    best = None
    for path in glob.glob(os.path.join(run_dir, "**", "checkpoint", "ckpt_*_*.ckpt"), recursive=True):
        m = re.search(r"ckpt_(\d+)_\d+\.ckpt$", os.path.basename(path))
        if not m:
            continue
        key = (int(m.group(1)), os.path.getmtime(path))
        if best is None or key > best[0]:
            best = (key, path)
    return (best[0][0], best[1]) if best else (0, None)


def rss_gb(pid: int) -> float:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024 / 1024
    except OSError:
        pass
    return 0.0


def stop(proc: subprocess.Popen, grace_s: float = 90.0) -> None:
    if proc.poll() is not None:
        return
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=grace_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--run-dir", required=True, help="where checkpoints land (searched recursively)")
    ap.add_argument("--chain-dir", required=True, help="chain state: leg logs + status file")
    ap.add_argument("--target-step", type=int, required=True)
    ap.add_argument("--deadline-ts", type=float, required=True, help="unix ts: no legs past this; running leg is stopped")
    ap.add_argument("--leg-seconds", type=float, default=7200)
    ap.add_argument("--max-rss-gb", type=float, default=85)
    ap.add_argument("--max-failures", type=int, default=3)
    ap.add_argument("--poll-seconds", type=float, default=30)
    ap.add_argument("overrides", nargs="+", help="sheeprl.py overrides (after --)")
    args = ap.parse_args()

    os.makedirs(args.chain_dir, exist_ok=True)
    status_path = os.path.join(args.chain_dir, "status.jsonl")

    def note(**kw):
        kw["ts"] = round(time.time(), 1)
        with open(status_path, "a") as f:
            f.write(json.dumps(kw) + "\n")
        print(json.dumps(kw), flush=True)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    failures = 0
    leg = 0
    # number legs after any the chain dir already has (chain restart safety)
    existing = glob.glob(os.path.join(args.chain_dir, "leg_*.log"))
    if existing:
        leg = max(int(re.search(r"leg_(\d+)\.log$", p).group(1)) for p in existing) + 1

    while True:
        step, ckpt = latest_ckpt(args.run_dir)
        if step >= args.target_step:
            note(event="target_reached", step=step, ckpt=ckpt)
            return 0
        now = time.time()
        if now >= args.deadline_ts:
            note(event="deadline", step=step)
            return 0
        if failures >= args.max_failures:
            note(event="too_many_failures", step=step)
            return 1

        leg_log = os.path.join(args.chain_dir, f"leg_{leg:03d}.log")
        cmd = [sys.executable, os.path.join(repo, "sheeprl.py"), *args.overrides,
               f"run_name=chain_leg{leg:03d}"]
        if ckpt:
            cmd.append(f"checkpoint.resume_from={ckpt}")
        note(event="leg_start", leg=leg, from_step=step, ckpt=ckpt)
        t_leg = time.time()
        # unbuffered: reward lines must reach the log file as they happen,
        # or a SIGKILL'd leg loses the buffered tail the curve stitcher needs
        leg_env = {**os.environ, "PYTHONUNBUFFERED": "1"}
        with open(leg_log, "a") as lf:
            proc = subprocess.Popen(cmd, stdout=lf, stderr=lf, cwd=repo, env=leg_env)
            reason = "exit"
            while proc.poll() is None:
                time.sleep(args.poll_seconds)
                elapsed = time.time() - t_leg
                mem = rss_gb(proc.pid)
                if time.time() >= args.deadline_ts:
                    reason = "deadline"
                    stop(proc)
                elif elapsed > args.leg_seconds:
                    reason = "leg_wallclock"
                    stop(proc)
                elif mem > args.max_rss_gb:
                    reason = "rss_cap"
                    stop(proc)
        new_step, _ = latest_ckpt(args.run_dir)
        made_progress = new_step > step
        failures = 0 if made_progress else failures + 1
        note(event="leg_end", leg=leg, reason=reason, rc=proc.returncode,
             leg_s=round(time.time() - t_leg, 1), from_step=step, to_step=new_step,
             made_progress=made_progress)
        leg += 1


if __name__ == "__main__":
    sys.exit(main())
