#!/bin/bash
# End-of-run pipeline for the round-4 Plan2Explore (DV3) two-phase run on
# dm_control cartpole swingup_sparse. Stitches BOTH phases into one
# artifact via finalize_curve.py: the finetuning phase's reward curve +
# greedy eval, plus the exploration phase's task-reward trace (expected
# ~0 on a sparse task — the policy optimizes ensemble disagreement, not
# reward). Run AFTER the finetune chain has stopped.
set -e -o pipefail
cd /root/repo
exec python scripts/finalize_curve.py \
  --chain-dir runs/p2e_sparse/chain_fntn \
  --run-dir runs/p2e_sparse/fntn \
  --expl-chain-dir runs/p2e_sparse/chain_expl \
  --out benchmarks/results/p2e_dv3_cartpole_sparse_r4.json \
  --experiment "p2e_dv3 two-phase on dm_control cartpole swingup_sparse (DV3-S, pixels, 8 envs): exploration phase trains world model + ensemble on intrinsic disagreement reward only, finetuning resumes from the exploration checkpoint with the inherited buffer and trains the task actor/critic on extrinsic reward" \
  --protocol "both phases trained FROM SCRATCH this round via scripts/train_chain.py; finetune curve = episode-end rewards binned from stdout; first learning-evidence artifact for the Plan2Explore family"
