#!/bin/bash
# End-of-run pipeline for the round-4 Plan2Explore (DV3) two-phase run on
# dm_control cartpole swingup_sparse. Stitches BOTH phases into one
# artifact: the exploration phase's task-reward trace (expected ~0 on a
# sparse task — the policy optimizes ensemble disagreement, not reward)
# and the finetuning phase's reward curve + greedy eval (the claim under
# test: the disagreement-driven buffer + world model make the sparse task
# solvable where random exploration rarely even sees reward).
set -e -o pipefail
cd /root/repo
OUT=benchmarks/results/p2e_dv3_cartpole_sparse_r4.json

python scripts/curve_from_logs.py \
  --chain-dir runs/p2e_sparse/chain_fntn \
  --out "$OUT"

CKPT=$(python - <<'EOF'
from scripts.train_chain import latest_ckpt
step, ckpt = latest_ckpt("runs/p2e_sparse/fntn")
print(ckpt)
EOF
)
if [ -z "$CKPT" ] || [ "$CKPT" = "None" ]; then
  echo "ERROR: no finetune checkpoint under runs/p2e_sparse/fntn" >&2
  exit 1
fi
CKPT_STEP=$(basename "$CKPT" | sed -E 's/ckpt_([0-9]+)_.*/\1/')
FINAL_STEP=$(python -c "import json,sys; print(json.load(open('$OUT'))['final_step'])")
DELTA=$((CKPT_STEP - FINAL_STEP)); DELTA=${DELTA#-}
if [ "$DELTA" -gt 26000 ]; then
  echo "ERROR: newest ckpt step $CKPT_STEP is $DELTA steps from the curve's final step $FINAL_STEP" >&2
  exit 1
fi
echo "evaluating $CKPT"
MUJOCO_GL=egl timeout 1200 python sheeprl_eval.py "checkpoint_path=$CKPT" \
  env.capture_video=False 2>&1 | tee /tmp/p2e_eval_r4.log | tail -3

python - "$OUT" "$CKPT_STEP" <<'EOF'
import glob, json, re, sys
out, ckpt_step = sys.argv[1], int(sys.argv[2])
d = json.load(open(out))
txt = open("/tmp/p2e_eval_r4.log").read()
m = re.findall(r"Test - Reward: ([-\d.]+)", txt)
if not m:
    sys.exit("ERROR: no 'Test - Reward:' line in the eval log — refusing to "
             "publish the artifact without the greedy-eval number")
d["greedy_eval_reward_at_final_ckpt"] = float(m[-1])
d["eval_ckpt_step"] = ckpt_step

# exploration-phase task-reward trace (from the exploration run's log):
# near-zero rewards here are the POINT — they show the sparse task gives
# random/intrinsic behavior almost no signal
expl_rewards = []
for p in sorted(glob.glob("runs/p2e_sparse/expl_leg*.log")):
    for step, rew in re.findall(r"policy_step=(\d+), reward_env_\d+=([-\d.e]+)", open(p, errors="ignore").read()):
        expl_rewards.append({"step": int(step), "reward": float(rew)})
d["exploration_phase_task_rewards"] = expl_rewards
if expl_rewards:
    vals = [r["reward"] for r in expl_rewards]
    d["exploration_phase_summary"] = {
        "episodes": len(vals),
        "reward_mean": sum(vals) / len(vals),
        "reward_max": max(vals),
    }

d["experiment"] = ("p2e_dv3 two-phase on dm_control cartpole swingup_sparse "
                   "(DV3-S, pixels, 8 envs): exploration phase trains world model + "
                   "ensemble on intrinsic disagreement reward only, finetuning "
                   "resumes from the exploration checkpoint with the inherited "
                   "buffer and trains the task actor/critic on extrinsic reward")
d["hardware"] = "1x TPU v5e (tunneled axon backend) + 1-core CPU host"
json.dump(d, open(out, "w"), indent=2)
print(json.dumps({k: d.get(k) for k in ("final_step", "final_reward_mean", "best_reward_mean", "greedy_eval_reward_at_final_ckpt")}))
EOF
