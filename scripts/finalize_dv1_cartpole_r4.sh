#!/bin/bash
# End-of-chain pipeline for the round-4 DreamerV1 cartpole-balance run.
# Run AFTER the chain has stopped. Thin wrapper over finalize_curve.py
# (the shared stitch + sanity-check + greedy-eval pipeline).
set -e -o pipefail
cd /root/repo
exec python scripts/finalize_curve.py \
  --chain-dir runs/dv1_cartpole/chain_r4 \
  --run-dir runs/dv1_cartpole \
  --out benchmarks/results/dv1_cartpole_balance_curve_r4.json \
  --experiment "dreamer_v1_dmc_cartpole_balance (DreamerV1, dm_control cartpole-balance from 64x64 pixels, paper DMC recipe: deter 200 / stoch 30 / dense 400 / ELU, action_repeat 2, replay_ratio 0.2, 8 async envs, HBM replay cache)" \
  --protocol "trained FROM SCRATCH this round via scripts/train_chain.py checkpoint-resume legs; curve = episode-end rewards binned from stdout; first learning-evidence artifact for the DreamerV1 family (DV2: walker-walk r4; DV3: walker 742.8@100K r3, cartpole-swingup 865.5@204K r4, ball_in_cup 916@100K r4)"
