#!/usr/bin/env python3
"""Repo entry point for the jaxlint static pass (ISSUE 9).

Equivalent invocations::

    python scripts/jaxlint.py sheeprl_tpu/
    python -m sheeprl_tpu.analysis sheeprl_tpu/
    jaxlint sheeprl_tpu/          # console script (pip install -e .)

See ``howto/static-analysis.md`` for the checker catalog, suppression
syntax and baseline semantics.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sheeprl_tpu.analysis.lint import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
