#!/bin/bash
# Round-3 session transition: stop the walker chain, stitch its curve,
# launch the cartpole-swingup (dense) chain. Run from the repo root.
set -x
cd /root/repo

# 1. stop walker chain + leg
ps -eo pid,args | grep -E "train_chain|run_name=chain_leg" | grep -v grep | awk '{print $1}' | while read p; do kill "$p" 2>/dev/null; done
sleep 10
ps -eo pid,args | grep -E "train_chain|run_name=chain_leg" | grep -v grep | awk '{print $1}' | while read p; do kill -9 "$p" 2>/dev/null; done

# 2. stitch the walker curve artifact
python scripts/curve_from_logs.py --chain-dir runs/dv3_walker/chain_r3b \
  --out benchmarks/results/dv3_walker_walk_curve_r3.json

# 3. launch the cartpole dense chain (deadline ~19:50 UTC = 1785527400)
mkdir -p runs/dv3_cartpole/chain_r3
MUJOCO_GL=egl SHEEPRL_STACK_DUMP_S=60 SHEEPRL_STACK_DUMP_FILE=/tmp/cartpole_stacks.log \
nohup python scripts/train_chain.py \
  --run-dir runs/dv3_cartpole --chain-dir runs/dv3_cartpole/chain_r3 \
  --target-step 200000 --deadline-ts 1785527400 \
  --leg-seconds 7200 --max-rss-gb 38 --max-failures 4 \
  -- exp=dreamer_v3_dmc_cartpole_swingup env.num_envs=8 env.capture_video=False \
     algo.replay_ratio=0.3 buffer.size=100000 buffer.memmap=False \
     checkpoint.every=4000 checkpoint.keep_last=3 metric.log_every=2000 \
     metric.fetch_every=8 \
     root_dir=/root/repo/runs/dv3_cartpole \
  > runs/dv3_cartpole/chain_r3/chain.out 2>&1 &
disown
echo "cartpole chain launched"
