"""Saturation swarm: drive a served policy with hundreds of session clients.

The CLI face of :func:`sheeprl_tpu.scale.swarm.run_swarm` (howto/
serving.md "Autoscaling"): N threaded SessionClients with HEAVY-TAILED
(lognormal) think times step a served recurrent policy to saturation,
recording per-client latency histograms and a p99 SLO verdict through
the PR-16 tracker.  Two targets:

- ``--checkpoint ckpt_*.ckpt`` — serve a trained recurrent checkpoint
  (recurrent PPO or Dreamer v3, the families scripts/serve_policy.py
  knows) behind ONE session server and swarm it;
- no checkpoint (the default) — a tiny synthetic recurrent-PPO module
  behind an ELASTIC ServePool (``--pool-min``/``--pool-max``) whose
  autoscaler grows and shrinks off the measured queue depth while the
  swarm runs: the quickest way to watch the whole elastic serving plane
  work on one box.

Examples::

    python scripts/swarm.py --clients 128 --steps 40 --pool-min 1 --pool-max 3
    python scripts/swarm.py --checkpoint logs/.../ckpt_1024_0.ckpt --clients 64
    python scripts/swarm.py --clients 64 --out benchmarks/results/swarm.json

The report JSON (``benchmarks/results/swarm_*.json`` row format) prints
on stdout; exit code 1 when requests were dropped or the p99 SLO
breached.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# runnable as `python scripts/swarm.py`
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def synthetic_session_parts(seed: int = 0, obs_dim: int = 4, hidden: int = 8):
    """A tiny recurrent-PPO module + session adapters, no checkpoint
    needed (shared with bench.py's swarm section and the scale chaos
    leg).  Returns ``(params, session_policy_fn, init_state_fn,
    obs_key, obs_dim)``."""
    import jax

    from sheeprl_tpu.algos.ppo_recurrent.agent import RecurrentPPOAgentModule
    from sheeprl_tpu.serve import make_recurrent_ppo_session_fns

    module = RecurrentPPOAgentModule(
        actions_dim=(2,),
        is_continuous=False,
        cnn_keys=(),
        mlp_keys=("state",),
        encoder_cfg=dict(
            cnn_features_dim=0, mlp_features_dim=16, dense_units=16,
            mlp_layers=1, dense_act="tanh", layer_norm=False,
        ),
        rnn_cfg={
            "lstm": {"hidden_size": hidden},
            "pre_rnn_mlp": {"apply": False, "dense_units": 8, "mlp_layers": 1,
                            "dense_act": "tanh", "layer_norm": False},
            "post_rnn_mlp": {"apply": False, "dense_units": 8, "mlp_layers": 1,
                             "dense_act": "tanh", "layer_norm": False},
        },
        actor_cfg=dict(dense_units=8, mlp_layers=1, dense_act="tanh", layer_norm=False),
        critic_cfg=dict(dense_units=8, mlp_layers=1, dense_act="tanh", layer_norm=False),
    )
    import jax.numpy as jnp
    import numpy as np

    k = jax.random.PRNGKey(seed)
    params = module.init(
        k,
        {"state": jnp.zeros((1, 1, obs_dim))},
        jnp.zeros((1, 1, 2)),
        jnp.ones((1, 1, 1)),
        jnp.zeros((1, hidden)),
        jnp.zeros((1, hidden)),
    )
    session_policy_fn, init_state_fn = make_recurrent_ppo_session_fns(module)
    del np
    return params, session_policy_fn, init_state_fn, "state", obs_dim


def warmup_buckets(session_fn, init_fn, params, obs_maker, max_batch: int) -> int:
    """Trace every power-of-two bucket once BEFORE the swarm starts, the
    way a production plane warms its traces at deploy: the report then
    measures steady-state serving, not the first batch's XLA compile.
    ``obs_maker(rows)`` builds one zero observation batch.  Returns the
    bucket count traced."""
    n = 0
    b = 1
    while b <= max_batch:
        state = init_fn(b, 0, params)
        session_fn(params, obs_maker(b), state)
        n += 1
        b *= 2
    return n


def run_pool_swarm(
    *,
    clients: int,
    steps: int,
    rows: int,
    think_mean_ms: float,
    think_sigma: float,
    pool_min: int,
    pool_max: int,
    seed: int = 0,
    deadline_ms: float = 2.0,
    max_batch: int = 16,
    slo_target_ms: float = 250.0,
    request_timeout_s: float = 1.0,
    session_capacity: int = 1024,
    session_ttl_s: float = 300.0,
):
    """The synthetic elastic-pool swarm (module docstring).  Returns
    ``(report, pool_stats)``."""
    import multiprocessing as mp

    from sheeprl_tpu.parallel.transport import make_transport
    from sheeprl_tpu.scale import Autoscaler, ServePool, run_swarm
    from sheeprl_tpu.serve.sessions import SessionInferenceServer

    import numpy as np

    params, session_fn, init_fn, obs_key, obs_dim = synthetic_session_parts(seed)
    warmup_buckets(
        session_fn, init_fn, params,
        lambda r: {obs_key: np.zeros((r, obs_dim), np.float32)},
        max_batch,
    )

    def factory(index: int, shared):
        return SessionInferenceServer(
            None,
            params,
            session_policy_fn=session_fn,
            init_state_fn=init_fn,
            shared=shared,
            capacity=session_capacity,
            idle_ttl_s=session_ttl_s,
            deadline_ms=deadline_ms,
            max_batch=max_batch,
            seed=seed,
            name=f"swarm-w{index}",
        )

    pool = ServePool(
        factory,
        min_workers=pool_min,
        max_workers=pool_max,
        autoscaler=Autoscaler(
            min_size=pool_min, max_size=pool_max,
            up_window_s=0.1, down_window_s=0.3,
            up_cooldown_s=0.2, down_cooldown_s=0.5,
            name="serve_pool",
        ),
        queue_high=4,
        queue_low=1,
    )
    pool.start()
    ctx = mp.get_context("spawn")
    hub, specs = make_transport(ctx, "queue", clients, window=8, min_bytes=0)
    for i in range(clients):
        pool.attach(i, hub.channel(i, timeout=5))
    try:
        report = run_swarm(
            [specs[i].player_channel() for i in range(clients)],
            steps=steps,
            rows=rows,
            obs_dim=obs_dim,
            obs_key=obs_key,
            think_mean_ms=think_mean_ms,
            think_sigma=think_sigma,
            seed=seed,
            client_kw={"request_timeout_s": request_timeout_s},
            slo_target_ms=slo_target_ms,
            control_tick=pool.control_tick,
        )
        stats = pool.stats()
    finally:
        pool.close()
        hub.close()
    return report, stats


def run_checkpoint_swarm(args):
    """Swarm one session server built from a trained checkpoint."""
    import multiprocessing as mp

    from scripts.serve_policy import build_server
    from sheeprl_tpu.parallel.transport import make_transport
    from sheeprl_tpu.scale import run_swarm
    from sheeprl_tpu.serve.sessions import SessionInferenceServer

    server, _, obs_keys, obs_space = build_server(
        args.checkpoint, greedy=False, deadline_ms=args.deadline_ms, max_batch=args.max_batch
    )
    if not isinstance(server, SessionInferenceServer):
        raise SystemExit(
            "swarm needs a recurrent family (recurrent PPO / Dreamer v3): "
            f"{args.checkpoint} built a stateless server"
        )
    import numpy as np

    def obs_fn(rng: "np.random.Generator", r: int):
        return [
            (k, rng.normal(size=(r,) + tuple(obs_space[k].shape)).astype(np.float32))
            for k in obs_keys
        ]

    warmup_buckets(
        server._session_policy_fn,
        server._init_state_fn,
        server.params,
        lambda r: {k: np.zeros((r,) + tuple(obs_space[k].shape), np.float32) for k in obs_keys},
        args.max_batch,
    )

    ctx = mp.get_context("spawn")
    hub, specs = make_transport(ctx, "queue", args.clients, window=8, min_bytes=0)
    for i in range(args.clients):
        server.attach(i, hub.channel(i, timeout=5))
    server.start()
    try:
        report = run_swarm(
            [specs[i].player_channel() for i in range(args.clients)],
            steps=args.steps,
            rows=args.rows,
            obs_fn=obs_fn,
            think_mean_ms=args.think_mean_ms,
            think_sigma=args.think_sigma,
            seed=args.seed,
            client_kw={"request_timeout_s": args.request_timeout},
            slo_target_ms=args.slo_target_ms,
        )
        stats = server.stats()
    finally:
        server.close()
        hub.close()
    return report, stats


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--checkpoint", default=None, help="recurrent ckpt_*.ckpt to serve (default: synthetic)")
    ap.add_argument("--clients", type=int, default=64)
    ap.add_argument("--steps", type=int, default=30, help="session steps per client")
    ap.add_argument("--rows", type=int, default=1)
    ap.add_argument("--think-mean-ms", type=float, default=2.0)
    ap.add_argument("--think-sigma", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--deadline-ms", type=float, default=2.0)
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--request-timeout", type=float, default=1.0)
    ap.add_argument("--slo-target-ms", type=float, default=250.0)
    ap.add_argument("--pool-min", type=int, default=1, help="synthetic mode: ServePool minimum workers")
    ap.add_argument("--pool-max", type=int, default=3, help="synthetic mode: ServePool maximum workers")
    ap.add_argument("--out", default=None, help="also write the report JSON here")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.checkpoint:
        report, stats = run_checkpoint_swarm(args)
    else:
        report, stats = run_pool_swarm(
            clients=args.clients,
            steps=args.steps,
            rows=args.rows,
            think_mean_ms=args.think_mean_ms,
            think_sigma=args.think_sigma,
            pool_min=args.pool_min,
            pool_max=args.pool_max,
            seed=args.seed,
            deadline_ms=args.deadline_ms,
            max_batch=args.max_batch,
            slo_target_ms=args.slo_target_ms,
            request_timeout_s=args.request_timeout,
        )
    out = dict(report.as_dict())
    out["server"] = {
        k: stats.get(k)
        for k in ("workers", "rebalanced", "requests", "dedup_hits", "sessions", "autoscale", "batch_hist")
        if k in stats
    }
    text = json.dumps(out, indent=2, default=str)
    print(text)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w") as f:
            f.write(text + "\n")
    ok = report.slo_ok and out.get("dropped", 1) == 0
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
