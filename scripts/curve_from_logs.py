"""Stitch a learning curve out of a train_chain.py run's leg logs.

Each leg log contains ``Rank-0: policy_step=N, reward_env_i=R`` lines;
legs overlap (a rotation replays the steps since the last checkpoint), so
later legs OVERRIDE earlier ones on overlapping step ranges.  Emits one
JSON artifact with the per-step mean/min/max across envs and a smoothed
mean, ready for benchmarks/results/.

Usage:
    python scripts/curve_from_logs.py --chain-dir runs/dv3_walker/chain_r3 \
        [--extra-log <earlier run log>] --out benchmarks/results/dv3_walker_curve_r3.json

Importable: ``stitch(chain_dir, extra_logs=(), smooth=5)`` returns the
artifact dict (used by scripts/finalize_curve.py so every end-of-chain
pipeline shares the resume-aware merge instead of re-parsing logs).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

LINE = re.compile(r"policy_step=(\d+), reward_env_(\d+)=([-+\d.eE]+)")


def parse_log(path):
    """-> {policy_step: {env_idx: reward}} for one leg log."""
    out = {}
    with open(path, errors="replace") as f:
        for line in f:
            m = LINE.search(line)
            if m:
                try:
                    rew = float(m.group(3))
                except ValueError:  # torn tail line from a SIGKILL'd leg
                    continue
                out.setdefault(int(m.group(1)), {})[int(m.group(2))] = rew
    return out


def stitch(chain_dir, extra_logs=(), smooth=5):
    """Merge a chain's leg logs (+ optional earlier-run logs) into one curve.

    Returns the artifact dict (source_logs/render_settings/n_points/
    final_step/final_reward_mean/best_reward_mean/curve).
    """
    # resume step per leg from the chain's status.jsonl: rewards are only
    # logged at episode ends, so a leg's first LOGGED step can be hundreds
    # of steps past its resume checkpoint — the override boundary must be
    # the checkpoint step or stale points blend into that window
    resume_step = {}
    status_path = os.path.join(chain_dir, "status.jsonl")
    if os.path.exists(status_path):
        with open(status_path, errors="replace") as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("event") == "leg_start":
                    resume_step[int(ev["leg"])] = int(ev.get("from_step") or 0)

    merged = {}
    chain_logs = sorted(glob.glob(os.path.join(chain_dir, "leg_*.log")))
    # --extra-log boundaries are each file's own first step, so files passed
    # out of chronological order would silently delete later data; sort them
    # by first parsed step before merging
    cache = {p: parse_log(p) for p in extra_logs}
    extra = sorted(extra_logs, key=lambda p: min(cache[p] or {0: 0}))
    logs = list(extra) + chain_logs
    for path in logs:
        parsed = cache.get(path) or parse_log(path)
        if not parsed:
            continue
        # A later leg resumes from a checkpoint BEFORE the previous leg's
        # kill point and replays that range along a fresh trajectory, so it
        # overrides everything from its resume step on — episode ends land
        # on different (step, env) pairs, so a keywise update would blend
        # the abandoned trajectory's points into the replayed window.
        # status.jsonl resume steps apply only to THIS chain's own legs;
        # --extra-log files (earlier runs) fall back to their first point.
        m = re.search(r"leg_(\d+)\.log$", os.path.basename(path)) if path in chain_logs else None
        first = resume_step.get(int(m.group(1)), min(parsed)) if m else min(parsed)
        dropped = [s for s in merged if s >= first]
        uncovered = [s for s in dropped if s > max(parsed)]
        if uncovered:
            print(
                f"WARNING: {os.path.basename(path)} (boundary {first}) deletes "
                f"{len(uncovered)} merged points beyond its own last step "
                f"{max(parsed)} (e.g. {uncovered[:3]}) — check log ordering",
                file=sys.stderr,
            )
        for step in dropped:
            del merged[step]
        for step, envs in parsed.items():
            merged.setdefault(step, {}).update(envs)

    points = []
    for step in sorted(merged):
        rews = list(merged[step].values())
        points.append(
            {
                "policy_step": step,
                "reward_mean": round(sum(rews) / len(rews), 2),
                "reward_min": round(min(rews), 2),
                "reward_max": round(max(rews), 2),
                "n_envs": len(rews),
            }
        )
    means = [p["reward_mean"] for p in points]
    w = max(1, smooth)
    for i, p in enumerate(points):
        lo = max(0, i - w + 1)
        p["reward_mean_smoothed"] = round(sum(means[lo : i + 1]) / (i + 1 - lo), 2)

    # disclose rendering settings that confound comparisons against the
    # reference's learning curves (ADVICE r3: dmc fast_render changes pixel
    # observations); read from any saved run config next to the chain dir
    render_cfg = None
    run_root = os.path.dirname(os.path.abspath(chain_dir.rstrip("/")))
    candidates = glob.glob(os.path.join(run_root, "chain_leg*", "**", "config.yaml"), recursive=True)
    # newest leg config = the one that actually produced the tail of the curve
    for cfg_path in sorted(candidates, key=os.path.getmtime, reverse=True)[:1]:
        try:
            with open(cfg_path) as f:
                for line in f:
                    if "fast_render" in line:
                        render_cfg = line.strip()
                        break
        except OSError:
            pass
    return {
        "source_logs": logs,
        "render_settings": render_cfg,
        "n_points": len(points),
        "final_step": points[-1]["policy_step"] if points else 0,
        "final_reward_mean": points[-1]["reward_mean"] if points else None,
        "best_reward_mean": max(means) if means else None,
        "curve": points,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--chain-dir", required=True)
    ap.add_argument(
        "--extra-log",
        action="append",
        default=[],
        help="logs from BEFORE the chain (e.g. the original run), applied first",
    )
    ap.add_argument("--out", required=True)
    ap.add_argument("--smooth", type=int, default=5, help="moving-average window (points)")
    args = ap.parse_args()

    artifact = stitch(args.chain_dir, args.extra_log, args.smooth)
    if os.path.dirname(args.out):
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
    print(
        json.dumps(
            {k: artifact[k] for k in ("n_points", "final_step", "final_reward_mean", "best_reward_mean")}
        )
    )


if __name__ == "__main__":
    main()
