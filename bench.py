"""Benchmark harness — prints one JSON metric line per benchmark for the driver.

Driver contract (hardened after round 2's rc=124 timeout):

- The ONLY bytes written to the real stdout are JSON metric lines.  All
  library noise (axon AOT-loader spam, compose trees, XLA warnings) goes
  to ``/tmp/sheeprl_bench.log``, so the driver's tail capture always ends
  with the metrics.
- Every section runs in its OWN subprocess with a hard timeout derived
  from the remaining budget (``BENCH_BUDGET_S``, default 480 s).  A
  section that hangs or dies cannot take the others down, and a fresh
  interpreter per section sidesteps an axon footgun where pre-initialized
  backends make later CLI runs recompile XLA:CPU executables on the
  single host core (~10x slowdown, observed round 3).
- Each metric is emitted exactly ONCE on stdout: non-dv3 sections the
  moment they finish, the flagship DV3 line deferred to the end so it
  closes the stream (the driver's tail parser reads the last lines).
  Every metric is also appended to ``benchmarks/results/bench_last.jsonl``
  the moment its section completes — a driver timeout can lose the tail
  sections but never completed ones — followed by one per-section
  telemetry summary record (XLA compile counts/time, compile-cache
  traffic, HBM usage, host RSS) from the obs layer.
- Fixed costs (tunnel backend init, tracing, XLA compiles) are separated
  from steady state: PPO and SAC run their CLI protocol FOUR times — a
  short run that pays the one-time costs (cold compile or cache load), the
  same short run twice more fully cached (min taken), and a longer cached run whose EXTRA
  steps over the cached short run are pure steady state — and the reported
  wall-clock is ``steady_rate x 65536``.  This is conservative: the
  protocol's cheaper warmup steps are billed at the full steady-state
  rate.  (Round 2's naive ``elapsed x 65536/n`` rescaling inflated fixed
  costs; differencing long-vs-COLD went negative on a fresh machine.)
- XLA executables hit the persistent compilation cache
  (``~/.cache/sheeprl_tpu_xla``, configured by MeshRuntime), so repeat
  runs pay trace+load (~10 s for DV3-S) rather than full compiles.

Benchmarks (baselines from BASELINE.md / the reference README):

1. PPO wall-clock — the reference's own benchmark protocol (reference
   benchmarks/benchmark.py + configs/exp/ppo_benchmarks.yaml): PPO on
   CartPole-v1, 1 env, 65536 total steps.  Baseline: 81.27 s
   (reference README.md:100-115, SheepRL v0.5.5, 1 device).
2. SAC wall-clock — reference configs/exp/sac_benchmarks.yaml:
   LunarLanderContinuous, 65536 steps, 1 gradient step per env step.
   ``algo.dispatch_batch=64`` batches 64 gradient steps into one jitted
   scan dispatch (same total work).  Baseline: 320.21 s (reference
   README.md:133-149).
3. Decoupled-vs-coupled speedup on the TPU-backed learner (PPO + SAC;
   the reference's flagship decoupled topology, ppo_decoupled.py:623-670).
4. DreamerV3-S replayed-frames/s of the full jitted train step on
   Atari-shaped pixels (B=16, T=64, 64x64x3), timed as the training loop
   runs it: chained async dispatches with one trailing host sync (the
   CLI's metric fetch is gated the same way).  Baseline: the reference's
   Atari-100K MsPacman run (README.md:44-51) — 100K gradient steps x
   1024 frames in 14 h on an RTX 3080 ~= 2032 replayed frames/s.  The
   line also carries ``step_ms`` and ``mfu_pct`` (achieved FLOP/s from
   XLA cost analysis vs the 197 TFLOP/s bf16 peak of one TPU v5e chip).

5. Replay-feed cost per gradient step at DV3-S shapes (the ``loop``
   section): host buffer sample + upload vs the HBM-resident cache's
   on-device gather (``data/device_buffer.py``).  Its ``vs_baseline`` is
   the host-over-device feed ratio on THIS machine's link (the reference
   pays ~0 feed cost over local PCIe).

``vs_baseline`` is the speedup factor (>1 is faster than the reference).

A perf-regression GATE runs after the sections (ROADMAP item 5): each
headline metric is compared against the newest committed ``BENCH_r*.json``
and a >20% regression in the metric's better-direction fails the run
loudly (stderr + exit 3).  Known-noisy metrics are exempt via the
justified skip-list in ``benchmarks/bench_gate_skiplist.json``.

Env overrides: BENCH_BUDGET_S, BENCH_SKIP_PPO/SAC/A2C/DV3/DEC/LOOP/FANIN/
JAXENV/CKPT/SUPERBENCH, BENCH_PPO_STEPS, BENCH_SAC_STEPS, BENCH_A2C_STEPS,
BENCH_DV3_STEPS, BENCH_FANIN_STEPS, BENCH_JAXENV_STEPS, BENCH_SUPER_STEPS,
BENCH_CKPT_MB (comma list of state sizes), BENCH_PLATFORM (cpu for local
tests), BENCH_SKIP_GATE, BENCH_GATE_THRESHOLD (fraction, default 0.20).
"""

import json
import os
import signal
import subprocess
import sys
import time

T_START = time.perf_counter()
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", 480))
REPO = os.path.dirname(os.path.abspath(__file__))
RESULTS_PATH = os.path.join(REPO, "benchmarks", "results", "bench_last.jsonl")
LOG_PATH = "/tmp/sheeprl_bench.log"
_CHILD_OUT_PATH = None  # set by child_main so long sections can persist partial metrics

REFERENCE_PPO_SECONDS = 81.27
REFERENCE_SAC_SECONDS = 320.21
REFERENCE_A2C_SECONDS = 84.76
REFERENCE_DV3_FRAMES_PER_S = 2032.0
FULL_STEPS = 65536
TPU_V5E_BF16_PEAK_FLOPS = 197e12

# (section, conservative wall-clock estimate used for skip decisions);
# ppo/sac cover four CLI runs each (cold + 2 cached-warm + long); dec runs
# five protocol ladders (coupled/decoupled x ppo/sac + queue/tcp transport
# A/Bs) on the TPU-backed learner; fanin scales the decoupled player count
SECTIONS = [
    ("dv3", 60),
    ("loop", 60),
    ("jaxenv", 60),
    ("replay", 120),
    ("ckpt", 60),
    ("serve", 90),
    ("ppo", 100),
    ("sac", 60),
    ("a2c", 100),
    ("swarm", 90),
    ("dec", 300),
    ("fanin", 140),
    ("transport", 240),
    ("wire", 160),
    ("mesh", 560),
    ("superbench", 200),
]


def _note(**kw):
    kw["t"] = round(time.perf_counter() - T_START, 1)
    try:
        os.makedirs(os.path.dirname(RESULTS_PATH), exist_ok=True)
        with open(RESULTS_PATH, "a") as f:
            f.write(json.dumps(kw) + "\n")
    except OSError:
        pass


# --------------------------------------------------------------- sections
# Each runs inside a fresh child interpreter (see __main__) and returns the
# metric dict.  Children must NOT touch jax backends before the first
# MeshRuntime launch (the axon footgun above).


def _cli_steady_rate(overrides, n_warm, n_long):
    """Seconds per policy step in steady state for a CLI protocol.

    Runs the protocol at ``n_warm`` steps three times — the first pays every
    one-time cost (backend init, tracing, XLA compile or persistent-cache
    load, env creation), the next two hit all caches (min kept) — and once
    at ``n_long`` steps.  The extra ``n_long - n_warm`` steps of the long
    run over the *cached* warm run are pure steady state.  Differencing
    against the cold first run instead would go NEGATIVE on a fresh
    machine (cold compiles dwarf the extra steps — observed round 3:
    rate clamped to ~0 and the vs_baseline division blew up), so the
    cold run is used for nothing but warming.  Any residual fixed cost
    the long run pays only makes the estimate more conservative.
    """
    from sheeprl_tpu.cli import run

    tic = time.perf_counter()
    run(overrides + [f"algo.total_steps={n_warm}"])
    t_cold = time.perf_counter() - tic
    # two cached warm legs, keep the MIN: a single noise-inflated warm run
    # would make (t_long - t_warm) arbitrarily small-but-positive and
    # silently exaggerate the extrapolated speedup
    t_warms = []
    for _ in range(2):
        tic = time.perf_counter()
        run(overrides + [f"algo.total_steps={n_warm}"])
        t_warms.append(time.perf_counter() - tic)
    t_warm = min(t_warms)
    tic = time.perf_counter()
    run(overrides + [f"algo.total_steps={n_long}"])
    t_long = time.perf_counter() - tic
    # physical sanity floor: the extra (n_long - n_warm) steps cannot
    # plausibly cost less than 20% of the long run's pro-rata share; below
    # that, bill the long run pro-rata instead of trusting the difference
    steady = t_long - t_warm
    floor = 0.2 * t_long * (n_long - n_warm) / n_long
    if steady < floor:
        steady = t_long * (n_long - n_warm) / n_long
    rate = max(steady / (n_long - n_warm), 1e-5)
    return rate, t_cold, t_warm, t_long


def bench_ppo():
    n_long = max(int(os.environ.get("BENCH_PPO_STEPS", 33280)), 256)
    n_warm = max(min(1024, n_long // 2), 128)
    rate, t_cold, t_warm, t_long = _cli_steady_rate(
        ["exp=ppo_benchmarks", "root_dir=/tmp/sheeprl_tpu_bench/ppo"], n_warm, n_long
    )
    # paired A/B: same protocol with the collect/train overlap pipeline on
    # (ISSUE 3) — the ratio is the overlap's steady-state win on this host
    rate_ov, *_ = _cli_steady_rate(
        [
            "exp=ppo_benchmarks",
            "algo.overlap_collect=True",
            "root_dir=/tmp/sheeprl_tpu_bench/ppo_ov",
        ],
        n_warm,
        n_long,
    )
    value = round(rate * FULL_STEPS, 2)
    return {
        "metric": "ppo_cartpole_benchmark_wallclock",
        "value": value,
        "unit": "s",
        "vs_baseline": round(REFERENCE_PPO_SECONDS / value, 3),
        "method": f"steady-state {n_long - n_warm} steps x {rate * 1e3:.3f} ms/step -> 65536",
        "measured_s": [round(t_cold, 2), round(t_warm, 2), round(t_long, 2)],
        "overlap_ms_per_step": round(rate_ov * 1e3, 3),
        "serial_ms_per_step": round(rate * 1e3, 3),
        "overlap_speedup": round(rate / rate_ov, 3),
        # the overlap needs host cores for the collector thread to run ON
        # — on a 1-core host it degenerates to time-slicing + handoff
        # overhead and CANNOT beat serial (same caveat as bench_dec)
        "host_cpu_count": os.cpu_count(),
    }


def bench_a2c():
    """A2C wall-clock — reference configs/exp/a2c_benchmarks.yaml
    (reference README.md:116-132): CartPole-v1, 1 env, 65536 steps.
    Baseline: 84.76 s (BASELINE.md)."""
    n_long = max(int(os.environ.get("BENCH_A2C_STEPS", 33280)), 256)
    n_warm = max(min(1024, n_long // 2), 128)
    rate, t_cold, t_warm, t_long = _cli_steady_rate(
        ["exp=a2c_benchmarks", "root_dir=/tmp/sheeprl_tpu_bench/a2c"], n_warm, n_long
    )
    # paired A/B: overlap pipeline on (ISSUE 3)
    rate_ov, *_ = _cli_steady_rate(
        [
            "exp=a2c_benchmarks",
            "algo.overlap_collect=True",
            "root_dir=/tmp/sheeprl_tpu_bench/a2c_ov",
        ],
        n_warm,
        n_long,
    )
    # paired A/B (ISSUE 15): the live metrics plane's overhead on the SAME
    # loop.  Both legs run with telemetry ON (the benchmark config
    # disables it, and live rides the telemetry record path — with it off
    # there would be nothing to measure); metric.live is the ONLY delta,
    # so the ratio isolates the hub tee + alert rules + endpoint thread.
    tele = ["metric.log_level=1", "metric.log_every=5000", "metric.disable_timer=False"]
    rate_tel, *_ = _cli_steady_rate(
        ["exp=a2c_benchmarks", *tele, "root_dir=/tmp/sheeprl_tpu_bench/a2c_tel"],
        n_warm,
        n_long,
    )
    rate_live, *_ = _cli_steady_rate(
        [
            "exp=a2c_benchmarks",
            *tele,
            "metric.live=on",
            "root_dir=/tmp/sheeprl_tpu_bench/a2c_live",
        ],
        n_warm,
        n_long,
    )
    # paired A/B (ISSUE 16): the streaming time ledger's overhead on the
    # SAME loop — metric.ledger is the only delta vs the telemetry leg,
    # so the ratio isolates the span-stack pushes/pops + bucket banking.
    rate_ledger, *_ = _cli_steady_rate(
        [
            "exp=a2c_benchmarks",
            *tele,
            "metric.ledger=on",
            "root_dir=/tmp/sheeprl_tpu_bench/a2c_ledger",
        ],
        n_warm,
        n_long,
    )
    value = round(rate * FULL_STEPS, 2)
    return {
        "metric": "a2c_cartpole_benchmark_wallclock",
        "value": value,
        "unit": "s",
        "vs_baseline": round(REFERENCE_A2C_SECONDS / value, 3),
        "method": f"steady-state {n_long - n_warm} steps x {rate * 1e3:.3f} ms/step -> 65536",
        "measured_s": [round(t_cold, 2), round(t_warm, 2), round(t_long, 2)],
        "overlap_ms_per_step": round(rate_ov * 1e3, 3),
        "serial_ms_per_step": round(rate * 1e3, 3),
        "overlap_speedup": round(rate / rate_ov, 3),
        "telemetry_ms_per_step": round(rate_tel * 1e3, 3),
        "live_on_ms_per_step": round(rate_live * 1e3, 3),
        # the ISSUE 15 <2% bound (single-run pairs swing a few % on this
        # 1-core box — the committed obs_live_r15.json holds the
        # interleaved min-of-N measurement the bound was proven with)
        "live_overhead_pct": round((rate_live / rate_tel - 1.0) * 100.0, 2),
        "ledger_ms_per_step": round(rate_ledger * 1e3, 3),
        # the ISSUE 16 <2% bound, same single-run-pair noise caveat
        "ledger_overhead_pct": round((rate_ledger / rate_tel - 1.0) * 100.0, 2),
        "host_cpu_count": os.cpu_count(),
    }


def bench_sac():
    n_long = max(int(os.environ.get("BENCH_SAC_STEPS", 9216)), 256)
    n_warm = max(min(1024, n_long // 2), 128)
    rate, t_cold, t_warm, t_long = _cli_steady_rate(
        [
            "exp=sac_benchmarks",
            "algo.dispatch_batch=64",
            "root_dir=/tmp/sheeprl_tpu_bench/sac",
        ],
        n_warm,
        n_long,
    )
    value = round(rate * FULL_STEPS, 2)
    return {
        "metric": "sac_lunarlander_benchmark_wallclock",
        "value": value,
        "unit": "s",
        "vs_baseline": round(REFERENCE_SAC_SECONDS / value, 3),
        "method": f"steady-state {n_long - n_warm} steps x {rate * 1e3:.3f} ms/step -> 65536",
        "measured_s": [round(t_cold, 2), round(t_warm, 2), round(t_long, 2)],
    }


def bench_dv3():
    from benchmarks.bench_dv3_step import time_variant

    steps = int(os.environ.get("BENCH_DV3_STEPS", 48))
    from sheeprl_tpu.obs import mfu_percent, peak_flops

    dt, t_len, b_size, extras = time_variant(
        fused=False,
        precision="bf16-mixed",
        steps=steps,
        cost_analysis=True,
        sync_every_step=False,
    )
    frames_per_s = t_len * b_size / dt
    flops = extras.get("flops_per_step")
    # generic MFU from the obs layer: detected device peak when known,
    # else the TPU v5e anchor every earlier round reported against
    mfu = mfu_percent(flops, dt, peak=peak_flops() or TPU_V5E_BF16_PEAK_FLOPS)
    return {
        "metric": "dreamer_v3_S_train_replayed_frames_per_s",
        "value": round(frames_per_s, 1),
        "unit": "frames/s",
        "vs_baseline": round(frames_per_s / REFERENCE_DV3_FRAMES_PER_S, 3),
        "step_ms": round(dt * 1e3, 1),
        "mfu_pct": round(mfu, 2) if mfu else None,
        # r4: the benched config now matches the BASELINE.md anchor
        # (dreamer_v3_100k_ms_pacman): DISCRETE actions.  r1-r3 benched a
        # continuous-action variant of the same S size (heavier: dynamics
        # backprop through imagination); r4 numbers for that variant are in
        # benchmarks/results/dv3_profile_r4.json for apples-to-apples.
        "config": f"T={t_len},B={b_size},"
        + ("continuous(6)" if os.environ.get("SHEEPRL_BENCH_CONTINUOUS", "0") == "1" else "discrete(6)")
        + ",bf16-mixed",
        "flops_per_step": flops,
    }


def _last_transport_telemetry(root_dir):
    """Newest decoupled run's last telemetry ``transport`` record under
    ``root_dir`` (payload accounting for the dec/fanin metric lines)."""
    import glob

    paths = sorted(
        glob.glob(os.path.join(root_dir, "**", "telemetry.jsonl"), recursive=True),
        key=os.path.getmtime,
    )
    last = None
    for line in open(paths[-1]) if paths else ():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if "transport" in rec:
            last = rec["transport"]
    return last


def _payload_bytes_per_iter(transport_rec):
    if not transport_rec:
        return None
    frames = max(sum(p.get("frames", 0) for p in transport_rec["players"].values()), 1)
    rollout_bytes = sum(p.get("bytes_in", 0) for p in transport_rec["players"].values())
    return int(rollout_bytes * len(transport_rec["players"]) / frames)


def bench_dec():
    """Coupled vs decoupled (CPU-player / TPU-learner) on the same chip.

    The decoupled topology is the reference's flagship scaling story
    (reference sheeprl/algos/ppo/ppo_decoupled.py:623-670): the player
    subprocess pins acting to the host CPU while the trainer keeps the
    chip busy, so link latency overlaps with training.  NOTE the overlap
    needs host cores to run the two processes on — on a 1-core host
    (os.cpu_count() is recorded in the metric) the split degenerates to
    time-slicing + IPC overhead and decoupled CANNOT beat coupled; the
    section still runs to prove the topology works end-to-end on the TPU
    and to quantify the penalty/win for the host it runs on."""
    results = {}

    def _metric():
        # vs_baseline deliberately None: this ratio is SELF-relative
        # (decoupled vs coupled on the same machine), not a speedup vs the
        # reference implementation like every other section's field
        ppo = results.get("ppo")
        return {
            "metric": "decoupled_over_coupled_speedup",
            "value": ppo["decoupled_speedup"] if ppo else None,
            "unit": "x",
            "vs_baseline": None,
            "host_cpu_count": os.cpu_count(),
            **results,
        }

    for algo, exp, n_warm, n_long in (
        ("ppo", "ppo_benchmarks", 512, 3072),
        ("sac", "sac_benchmarks", 256, 1024),
    ):
        base = [
            f"exp={exp}",
            "fabric.accelerator=auto",
            f"root_dir=/tmp/sheeprl_tpu_bench/dec_{algo}",
        ]
        r_c, *_ = _cli_steady_rate(base + ["run_name=coupled"], n_warm, n_long)
        r_d, *_ = _cli_steady_rate(
            base + [f"algo.name={algo}_decoupled", "run_name=decoupled"], n_warm, n_long
        )
        # payload accounting (ISSUE 4) from a short UNTIMED run with
        # telemetry on (the timed legs keep the benchmark's log_level=0):
        # keeps BENCH_r*.json trajectories comparable across transports
        from sheeprl_tpu.cli import run as _cli_run

        _cli_run(
            base
            + [
                f"algo.name={algo}_decoupled",
                "run_name=decoupled_acct",
                "metric.log_level=1",
                f"algo.total_steps={n_warm}",
            ]
        )
        tr = _last_transport_telemetry(f"/tmp/sheeprl_tpu_bench/dec_{algo}")
        results[algo] = {
            "coupled_ms_per_step": round(r_c * 1e3, 3),
            "decoupled_ms_per_step": round(r_d * 1e3, 3),
            "decoupled_speedup": round(r_c / r_d, 3),
            "transport": os.environ.get("SHEEPRL_DECOUPLED_TRANSPORT", "shm"),
            "num_players": int(tr["num_players"]) if tr else 1,
            "payload_bytes_per_iter": _payload_bytes_per_iter(tr),
        }
        if algo == "ppo":
            # transport A/B ladder (ISSUE 3 + 4): the same decoupled pair
            # over the legacy pickled queue and the new socket stream
            for leg, env_val in (("queue", "queue"), ("tcp", "tcp")):
                os.environ["SHEEPRL_DECOUPLED_TRANSPORT"] = env_val
                try:
                    r_leg, *_ = _cli_steady_rate(
                        base + [f"algo.name={algo}_decoupled", f"run_name=decoupled_{leg}"],
                        n_warm,
                        n_long,
                    )
                finally:
                    os.environ.pop("SHEEPRL_DECOUPLED_TRANSPORT", None)
                results[algo][f"{leg}_ms_per_step"] = round(r_leg * 1e3, 3)
            results[algo]["shm_over_queue_speedup"] = round(
                results[algo]["queue_ms_per_step"] / (r_d * 1e3), 3
            )
            results[algo]["tcp_over_queue_speedup"] = round(
                results[algo]["queue_ms_per_step"] / results[algo]["tcp_ms_per_step"], 3
            )
        # durability: the dec section is the longest — persist after each
        # completed protocol pair so a timeout can't lose finished work
        if _CHILD_OUT_PATH:
            try:
                with open(_CHILD_OUT_PATH, "w") as f:
                    json.dump(_metric(), f)
            except OSError:
                pass
    return _metric()


def bench_fanin():
    """N-player rollout fan-in scaling (ISSUE 4): decoupled PPO at
    N=1/2/4 players over the socket transport.  On a 1-core container
    every player time-slices the same core, so the scaling ratio is a
    LOWER BOUND that mainly proves the fan-in works end to end — same
    caveat as the overlap/dec sections (host_cpu_count is recorded)."""
    from benchmarks.bench_fanin_scaling import _run_once

    steps = int(os.environ.get("BENCH_FANIN_STEPS", 1536))
    warm = max(steps // 3, 256)
    root = "/tmp/sheeprl_tpu_bench/fanin"
    rows = []
    for n in (1, 2, 4):
        _run_once("tcp", n, warm, root)  # compile/spawn warmup
        t_warm = _run_once("tcp", n, warm, root)
        t_long = _run_once("tcp", n, steps, root)
        steady = max(t_long - t_warm, 1e-6)
        sps = (steps - warm) / steady
        rows.append({"num_players": n, "steady_sps": round(sps, 1)})
        if n == 4:  # one untimed accounting run with telemetry on
            _run_once("tcp", n, warm, root, log_level=1)
        if _CHILD_OUT_PATH:
            try:
                with open(_CHILD_OUT_PATH, "w") as f:
                    json.dump({"metric": "fanin_scaling_partial", "players": rows}, f)
            except OSError:
                pass
    tr = _last_transport_telemetry(root)
    return {
        "metric": "decoupled_fanin_scaling_4p_over_1p",
        "value": round(rows[-1]["steady_sps"] / max(rows[0]["steady_sps"], 1e-6), 3),
        "unit": "x",
        # self-relative scaling ratio, not a reference comparison
        "vs_baseline": None,
        "transport": "tcp",
        "players": rows,
        "payload_bytes_per_iter": _payload_bytes_per_iter(tr),
        "host_cpu_count": os.cpu_count(),
    }


def bench_transport():
    """CRC-overhead legs of the transport ladder (ISSUE 10): the same
    Channel-API round trip with ``transport_integrity`` off vs crc, shm
    and tcp, at 0.25/1 MB payloads.  The sampled-coverage checksum
    exists to hold the overhead line (full-payload CRC32C measured ~35%
    of the 1 MB shm leg on this host class); what remains is a fixed
    ~25-30 us/message of python constants — 6-10% of the 1 MB ping-pong
    legs on a 1-core container, <5% from 4 MB up (howto/resilience.md
    "Data integrity" documents the breakdown).  The headline is the
    crc-mode 1 MB shm time so the perf-regression gate holds the line
    across rounds."""
    import tempfile

    from benchmarks.bench_shm_transport import run_integrity_ladder, run_tracing_ladder

    n_msgs = int(os.environ.get("BENCH_TRANSPORT_MSGS", 150))
    rows = run_integrity_ladder(n_msgs=n_msgs)
    top = rows[-1]  # the 1 MB row
    # paired flight-tracing leg (ISSUE 13): sampled tracing must hold <2%
    # on the 1 MB shm rung; the recorded flight streams double as a
    # trace-export smoke — obs.report merges them into a trace.json whose
    # path rides bench_last.jsonl
    flight_root = tempfile.mkdtemp(prefix="sheeprl_bench_flight_")
    trace_rows = run_tracing_ladder(n_msgs=n_msgs, flight_dir=flight_root)
    trace_path = None
    try:
        from sheeprl_tpu.obs.report import generate_report

        out_dir = os.path.join(REPO, "benchmarks", "results")
        os.makedirs(out_dir, exist_ok=True)
        trace_path = os.path.join(out_dir, "trace_last.json")
        generate_report(flight_root, out=trace_path)
    except Exception as e:  # the ladder numbers stand on their own
        print(f"trace export skipped: {type(e).__name__}: {e}", file=sys.stderr)
        trace_path = None
    finally:
        import shutil

        shutil.rmtree(flight_root, ignore_errors=True)
    return {
        "metric": "transport_crc_shm_1mb_ms",
        "value": round(top["shm_crc_us_per_msg"] / 1e3, 4),
        "unit": "ms",
        "vs_baseline": None,
        "shm_crc_overhead_pct": top["shm_crc_overhead_pct"],
        "tcp_crc_overhead_pct": top["tcp_crc_overhead_pct"],
        "checksum_impl": top["checksum_impl"],
        "coverage_bytes": top["coverage_bytes"],
        "tracing_shm_1mb_overhead_pct": trace_rows[-1]["shm_tracing_overhead_pct"],
        "tracing_rows": trace_rows,
        "trace_export_path": trace_path,
        "rows": rows,
        "host_cpu_count": os.cpu_count(),
    }


def bench_wire():
    """Wire-format v2 ladder (ISSUE 19): paired v1-vs-v2 legs through the
    real Channel API at tree-shaped rungs up to 1 MB / 32 leaves,
    streamed at a 6-frame window and interleaved min-of-N (the same
    noise protocol as the transport section).  The headline is the 1 MB
    tcp SPEEDUP of the scatter-gather codec over the pickled-metadata v1
    path (gated: higher is better, unit "x"), so a regression in the v2
    fast path — an extra copy sneaking into the gather list, a lost
    socket-buffer tune — fails the perf gate even while both codecs stay
    correct."""
    from benchmarks.bench_shm_transport import run_wire_ladder

    n_msgs = int(os.environ.get("BENCH_TRANSPORT_MSGS", 150))
    rows = run_wire_ladder(n_msgs=n_msgs)
    top = rows[-1]  # the 1 MB row
    return {
        "metric": "wire_v2_tcp_1mb_speedup_x",
        "value": top["tcp_v2_speedup_x"],
        "unit": "x",
        "vs_baseline": None,
        "tcp_v1_us_per_msg": top["tcp_v1_us_per_msg"],
        "tcp_v2_us_per_msg": top["tcp_v2_us_per_msg"],
        "shm_v2_speedup_x": top.get("shm_v2_speedup_x"),
        "rows": rows,
        "host_cpu_count": os.cpu_count(),
    }


def bench_mesh():
    """Sharded-train ladder (ISSUE 12): PPO + compact DV3 update step at
    1/2/4/8 host-platform mesh devices, DP and FSDP legs.  Runs in a
    dedicated subprocess because the virtual mesh needs
    ``xla_force_host_platform_device_count`` set BEFORE backend init,
    which this child cannot guarantee for itself.  On a 1-core container
    the ladder is a strong-scaling OVERHEAD measurement (ideal normalized
    step time ~1.0 at every size — see the bench module docstring); the
    headline is the 8-device DP PPO step so the perf-regression gate
    holds the partitioning-overhead line across rounds."""
    import subprocess
    import tempfile

    out = os.path.join(tempfile.mkdtemp(prefix="sheeprl_bench_mesh_"), "mesh.json")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    steps = os.environ.get("BENCH_MESH_STEPS", "4")
    subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "bench_sharded_train.py"),
         "--steps", steps, "--out", out],
        check=True,
        env=env,
        timeout=540,
    )
    with open(out) as f:
        data = json.load(f)
    legs = data["legs"]
    by = {(r["algo"], r["strategy"], r["devices"]): r for r in legs}
    head = by[("ppo", "dp", 8)]
    return {
        "metric": "mesh_ppo_dp8_step_ms",
        "value": head["step_ms"],
        "unit": "ms",
        "vs_baseline": None,
        "ppo_dp8_vs_ideal": head["achieved_vs_ideal"],
        "dv3_dp8_vs_ideal": by[("dv3", "dp", 8)]["achieved_vs_ideal"],
        "dv3_fsdp8_step_ms": by[("dv3", "fsdp", 8)]["step_ms"],
        "legs": legs,
        "host_cpu_count": os.cpu_count(),
    }


def bench_superbench():
    """The composed fleet (ISSUE 16): jax-env players x2 -> tcp fan-in ->
    dp8 mesh-sharded trainer, with flight spans, the live plane, and the
    streaming time ledger all ON.  Headline is FLEET frames/s (gated:
    higher is better); the line also names the run's ledger bottleneck so
    rounds compare on what the fleet waited for, not just how fast it
    went.  Dedicated subprocess for the same reason as mesh: the virtual
    8-device mesh needs ``xla_force_host_platform_device_count`` exported
    BEFORE backend init."""
    import subprocess
    import tempfile

    out = os.path.join(tempfile.mkdtemp(prefix="sheeprl_bench_super_"), "super.json")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    n_long = max(int(os.environ.get("BENCH_SUPER_STEPS", 1024)), 128)
    n_warm = max(min(256, n_long // 2), 64)
    subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "bench_superbench.py"),
         "--warm", str(n_warm), "--steps", str(n_long), "--out", out],
        check=True,
        env=env,
        timeout=540,
    )
    with open(out) as f:
        data = json.load(f)
    return {
        "metric": "superbench_fleet_frames_per_s",
        "value": data["fleet_frames_per_s"],
        "unit": "frames/s",
        "vs_baseline": None,
        "bottleneck": data["bottleneck"],
        "fleet_where_s": data["fleet_where_s"],
        "roles_with_ledger": data["roles_with_ledger"],
        "topology": data["topology"],
        "measured_s": [data["warm_s"], data["long_s"]],
        "host_cpu_count": os.cpu_count(),
    }


def bench_loop():
    """Replay-feed cost per gradient step at DV3-S shapes: host buffer
    sample + upload (what every gradient step paid before round 4's
    session 5) vs the HBM-resident cache's on-device gather
    (``data/device_buffer.py``).  This is the real-training-loop
    bottleneck on remote-link chips — the dv3 section's frames/s times a
    device-resident batch and cannot see it.  ``vs_baseline`` here is the
    host-feed-over-device-feed ratio on THIS machine's link (the
    reference pays ~0 feed cost over local PCIe, so a reference-relative
    number would be meaningless)."""
    import numpy as np
    import jax

    from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
    from sheeprl_tpu.data.device_buffer import DeviceReplayCache
    from sheeprl_tpu.data.feed import batched_feed
    from sheeprl_tpu.parallel.mesh import MeshRuntime

    platform = os.environ.get("BENCH_PLATFORM", "auto")
    runtime = MeshRuntime(accelerator=platform)
    runtime.launch()
    runtime.seed_everything(7)
    T, B, N_ENVS, CAP = 64, 16, 8, 2048
    rng = np.random.default_rng(0)
    rb = EnvIndependentReplayBuffer(CAP, n_envs=N_ENVS, buffer_cls=SequentialReplayBuffer)
    cache = DeviceReplayCache(CAP, N_ENVS, device=runtime.device)
    for t in range(CAP):
        row = {
            "rgb": rng.integers(0, 255, (1, N_ENVS, 64, 64, 3), dtype=np.uint8),
            "actions": rng.normal(size=(1, N_ENVS, 6)).astype(np.float32),
            "rewards": np.zeros((1, N_ENVS, 1), np.float32),
            "is_first": np.zeros((1, N_ENVS, 1), np.float32),
            "terminated": np.zeros((1, N_ENVS, 1), np.float32),
            "truncated": np.zeros((1, N_ENVS, 1), np.float32),
        }
        rb.add(row)
    cache.load_from(rb)  # one staged device_put per key — not 2048 appends

    def consume(batch):
        # force materialization on device (a gradient step would); returns
        # the on-device scalar so callers can chain without a host sync
        return jax.tree_util.tree_leaves(batch)[0].sum()

    def consume_sync(batch):
        # block on EVERY leaf: leaves[0] is the small 'actions' array, and
        # the 12.6MB rgb upload must finish inside the host-path timer
        jax.block_until_ready(batch)
        return float(jax.tree_util.tree_leaves(batch)[0].sum())

    def time_host(n):
        # the host path is inherently synchronous per draw (the upload is
        # the cost being measured), so per-iteration blocking is faithful
        tic = time.perf_counter()
        for _ in range(n):
            local = rb.sample(B, sequence_length=T, n_samples=1)
            with batched_feed(local, 1, sharding=runtime.batch_sharding(axis=1)) as feed:
                for b in feed:
                    consume_sync(b)
        return (time.perf_counter() - tic) / n

    def time_device(n):
        # chained async draws + ONE trailing sync — the way the training
        # loop consumes them; a per-draw host fetch would measure the
        # link RTT (~0.1 s here), not the gather
        tic = time.perf_counter()
        acc = None
        for _ in range(n):
            acc = consume(cache.sample(1, B, T, runtime.next_key())[0])
        float(acc)
        return (time.perf_counter() - tic) / n

    float(consume(cache.sample(1, B, T, runtime.next_key())[0]))  # compile
    time_host(1)
    host_s = time_host(4)
    dev_s = time_device(32)
    return {
        "metric": "dv3S_replay_feed_per_gradient_step_ms",
        "value": round(dev_s * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round(host_s / dev_s, 1),
        "host_feed_ms": round(host_s * 1e3, 1),
        "method": (
            "host: EnvIndependent/Sequential sample + prefetch device_put of the "
            "12.6MB T=64,B=16 uint8 pixel batch; device: DeviceReplayCache on-HBM "
            "gather; vs_baseline = host/device ratio on this machine's link"
        ),
        "platform": runtime.device.platform,
    }


def bench_ckpt():
    """Checkpoint-plane ladder (benchmarks/bench_ckpt.py, ISSUE 17):
    single-zip vs sharded-directory save/restore at state sizes x fsdp
    shard counts, interleaved min-of-N.  Headline is the widest rung's
    restore-locality ratio (full assemble over one rank's slice reads) —
    the portable signal on any host, since it counts bytes moved, not
    cores; the save fan-out ratios ride alongside and are LOWER bounds
    on a small container (thread-per-shard writers time-slice the cores
    a pod would dedicate per host)."""
    from benchmarks.bench_ckpt import run_ladder, summarize

    sizes = tuple(
        int(s) for s in os.environ.get("BENCH_CKPT_MB", "64,256").split(",")
    )
    rows = run_ladder(sizes_mb=sizes, n_iters=3)
    summary = summarize(rows)
    return {
        "metric": "sharded_ckpt_full_over_slice_restore",
        "value": summary["full_load_over_slice_load"],
        "unit": "x",
        # self-relative locality ratio, not a reference comparison
        "vs_baseline": None,
        "zip_over_sharded_save": summary["zip_over_sharded_save"],
        "zip_over_max_shard_save": summary["zip_over_max_shard_save"],
        "size_mb": summary["size_mb"],
        "fsdp": summary["fsdp"],
        "rows": rows,
        "host_cpu_count": os.cpu_count(),
    }


def bench_serve():
    """Inference-service ladder (benchmarks/bench_inference.py): request
    latency p50/p95 + actions/s for 1/2/4 env workers x batch deadline,
    remote (deadline-batched server over queue channels) vs a direct-call
    local policy baseline.  On this 1-core container the remote/local
    throughput ratio is a LOWER bound (server + workers + jit time-slice
    one core); the batch-size histogram shifting right with worker count
    is the portable batching signal."""
    from benchmarks.bench_inference import run_grid

    result = run_grid(n_requests=int(os.environ.get("BENCH_SERVE_REQUESTS", 256)))
    return {
        "metric": "inference_serving_remote_over_local_throughput",
        "value": result["remote_over_local_throughput"],
        "unit": "x",
        "best_remote": result["best_remote"],
        "local_actions_per_s": result["local_baseline"]["actions_per_s"],
        "remote_p50_ms": result["grid"][0]["client_latency_ms"]["p50"],
        "grid": result["grid"],
        "host_cpu_count": result["host_cpu_count"],
    }


def bench_swarm():
    """Saturation swarm vs the elastic in-process serve pool (scripts/
    swarm.py; howto/serving.md "Autoscaling"): a clients x think-time
    ladder of threaded session clients with lognormal think times drives
    a synthetic recurrent-PPO session server pool (min 1 / max 3
    workers) to saturation; per-rung actions/s, latency percentiles and
    the measured grow/shrink trajectory are recorded.  On this 1-core
    container every client thread, the pool workers and the jitted step
    time-slice one core, so absolute latency percentiles are an UPPER
    bound and the autoscaler mostly sees queue-depth pressure from GIL
    contention — the portable signals are zero dropped requests, the
    exactly-once session counters, and the grow/shrink events actually
    firing under load (host_cpu_count is recorded)."""
    from scripts.swarm import run_pool_swarm

    steps = int(os.environ.get("BENCH_SWARM_STEPS", 20))
    rows = []
    for clients, think_ms in ((16, 5.0), (48, 2.0), (96, 1.0)):
        report, stats = run_pool_swarm(
            clients=clients,
            steps=steps,
            rows=1,
            think_mean_ms=think_ms,
            think_sigma=1.0,
            pool_min=1,
            pool_max=3,
        )
        d = report.as_dict()
        scale = stats.get("autoscale") or {}
        rows.append(
            {
                "clients": clients,
                "think_mean_ms": think_ms,
                "steps_per_client": steps,
                "actions_per_s": d["actions_per_s"],
                "latency_ms": d["latency_ms"],
                "latency_hist": d["latency_hist"],
                "dropped": d["dropped"],
                "local_fallbacks": d["local_fallbacks"],
                "session_losses": d["session_losses"],
                "workers_final": stats.get("workers"),
                "grows": scale.get("grows"),
                "shrinks": scale.get("shrinks"),
                "slo_state": d["slo"]["swarm_p99"]["state"],
            }
        )
    heavy = rows[-1]
    return {
        "metric": "swarm_pool_actions_per_s_96c",
        "value": heavy["actions_per_s"],
        "unit": "actions/s",
        "vs_baseline": None,
        "dropped_total": sum(r["dropped"] for r in rows),
        "rows": rows,
        "host_cpu_count": os.cpu_count(),
    }


def bench_jaxenv():
    """Device-resident env ladder (benchmarks/bench_jaxenv.py, ISSUE 11):
    env-steps/s of host SyncVectorEnv vs JaxVectorEnv vs the fused
    collect (policy included) at 16/256/4096 parallel envs.  Headline is
    the 256-env fused-over-sync ratio (the >=10x acceptance bar); the
    fused legs also record their post-warmup compile delta, which must
    stay 0 — a retrace in the rollout program would silently eat the
    speedup on a real accelerator."""
    from benchmarks.bench_jaxenv import run_ladder

    rows = run_ladder(budget_steps=int(os.environ.get("BENCH_JAXENV_STEPS", 6400)))
    mid = next(r for r in rows if r["num_envs"] == 256)
    return {
        "metric": "jaxenv_fused_over_sync_speedup_256",
        "value": mid.get("fused_over_sync"),
        "unit": "x",
        # self-relative tier ratio on this host, not a reference comparison
        "vs_baseline": None,
        "fused_env_sps_256": mid["fused_env_sps"],
        "sync_env_sps_256": mid["sync_env_sps"],
        "post_warmup_compiles": sum(r["fused_post_warmup_compiles"] for r in rows),
        "rows": rows,
        "host_cpu_count": os.cpu_count(),
    }


def bench_replay():
    """Replay-sampling ladder (benchmarks/bench_replay_sampling.py):
    per-batch cost of the uniform vs prioritized on-device samplers at
    cache sizes 1e4 -> 1e6, in BOTH data-plane kernel modes
    (buffer.per_kernel=lax|pallas, interleaved min-of-N legs), plus the
    write-side costs prioritization adds and the params-digest cost
    ladder (host CRC walk vs the one-dispatch device digest).  The
    headline stays the r07-comparable largest-cache lax sample-cost
    ratio; the pallas legs ride alongside (the fused-exclusion descent's
    win shows on the next-obs legs, where the lax path pays a functional
    tree copy per draw)."""
    from benchmarks.bench_replay_sampling import run_digest_ladder, run_ladder

    rows = run_ladder(sizes=(10_000, 100_000, 1_000_000), batch=256, n_iters=10)
    digest_rows = run_digest_ladder()
    top = rows[-1]
    return {
        "metric": "prioritized_over_uniform_sample_cost_1e6",
        "value": top["prioritized_over_uniform"],
        "pallas_over_uniform": top["pallas_over_uniform"],
        "nobs_pallas_over_lax": top["nobs_pallas_over_lax"],
        "uniform_sample_ms": top["uniform_sample_ms"],
        "prioritized_sample_ms": top["prioritized_sample_ms"],
        "prioritized_pallas_ms": top["prioritized_pallas_ms"],
        "update_priorities_ms": top["update_priorities_ms"],
        "rows": rows,
        "digest_rows": digest_rows,
    }


# ------------------------------------------------------- perf-regression gate
# (ROADMAP item 5): every committed round leaves a BENCH_r*.json behind;
# the gate diffs this run's headline metrics against the newest one and
# fails LOUDLY on >20% regressions, so a perf cliff cannot slip through a
# green test suite.  Known-noisy metrics are exempted in an explicit,
# justified skip-list file (benchmarks/bench_gate_skiplist.json).

GATE_THRESHOLD = float(os.environ.get("BENCH_GATE_THRESHOLD", 0.20))
SKIPLIST_PATH = os.path.join(REPO, "benchmarks", "bench_gate_skiplist.json")

# which direction is better, keyed by the metric line's ``unit``
_LOWER_IS_BETTER_UNITS = ("s", "ms")
_HIGHER_IS_BETTER_UNITS = ("frames/s", "x", "steps/s", "actions/s")


def load_previous_round(repo=REPO):
    """Headline metrics of the newest committed ``BENCH_r*.json``:
    ``{metric: {"value": .., "unit": ..}}`` parsed from its ``tail`` of
    JSON lines (each metric's LAST occurrence wins — the driver re-emits
    deferred lines).  Returns ``(round_name, metrics)`` or ``(None, {})``."""
    import glob
    import re

    rounds = sorted(
        glob.glob(os.path.join(repo, "BENCH_r*.json")),
        key=lambda p: int(re.search(r"r(\d+)", os.path.basename(p)).group(1)),
    )
    if not rounds:
        return None, {}
    path = rounds[-1]
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return os.path.basename(path), {}
    metrics = {}
    for line in str(doc.get("tail", "")).splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if "metric" in rec and isinstance(rec.get("value"), (int, float)):
            metrics[rec["metric"]] = {"value": float(rec["value"]), "unit": rec.get("unit")}
    return os.path.basename(path), metrics


def load_gate_skiplist(path=SKIPLIST_PATH):
    try:
        with open(path) as f:
            return dict(json.load(f).get("skip", {}))
    except (OSError, ValueError):
        return {}


def run_perf_gate(current, repo=REPO, threshold=GATE_THRESHOLD):
    """Compare ``current`` (``{section: metric_dict}``) against the
    previous committed round.  Returns the gate record; ``regressions``
    non-empty means FAIL (the caller exits non-zero)."""
    baseline_name, baseline = load_previous_round(repo)
    skiplist = load_gate_skiplist()
    regressions, checked, skipped = [], [], []
    for metric_rec in current.values():
        name = metric_rec.get("metric")
        value = metric_rec.get("value")
        if not name or not isinstance(value, (int, float)):
            continue
        if name in skiplist:
            skipped.append(name)
            continue
        prev = baseline.get(name)
        if not prev or not prev["value"]:
            continue
        unit = metric_rec.get("unit") or prev.get("unit") or ""
        if unit in _LOWER_IS_BETTER_UNITS:
            change = value / prev["value"] - 1.0  # positive = slower = worse
        elif unit in _HIGHER_IS_BETTER_UNITS:
            change = prev["value"] / value - 1.0 if value else float("inf")
        else:
            continue  # unknown unit: no direction, no gate
        checked.append(name)
        if change > threshold:
            regressions.append(
                {
                    "metric": name,
                    "previous": prev["value"],
                    "current": value,
                    "unit": unit,
                    "regression_pct": round(change * 100, 1),
                }
            )
    return {
        "metric": "perf_regression_gate",
        "value": len(regressions),
        "unit": "regressions",
        "vs_baseline": None,
        "baseline_round": baseline_name,
        "threshold_pct": round(threshold * 100, 1),
        "checked": checked,
        "skipped": skipped,
        "regressions": regressions,
    }


def child_main(section, out_path):
    """Run one section with all output redirected to the log file."""
    global _CHILD_OUT_PATH
    _CHILD_OUT_PATH = out_path
    log_f = open(LOG_PATH, "a", buffering=1)
    os.dup2(log_f.fileno(), 1)
    os.dup2(log_f.fileno(), 2)
    sys.stdout = os.fdopen(os.dup(1), "w", buffering=1)
    sys.stderr = os.fdopen(os.dup(2), "w", buffering=1)
    sys.path.insert(0, REPO)

    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    else:
        # keep the host CPU backend available alongside the TPU so the
        # env-interaction player can run host-side (MeshRuntime.player_device).
        # Do NOT call jax.devices() here: backends must stay uninitialized
        # until the first MeshRuntime launch.
        try:
            current = jax.config.jax_platforms or "axon"
            if "cpu" not in current:
                jax.config.update("jax_platforms", f"{current},cpu")
        except Exception:
            pass

    # per-section telemetry summary (obs layer): compile counts/time,
    # compile-cache traffic, HBM + host RSS — appended to bench_last.jsonl
    # so a slow section can be attributed to compiles vs steady-state work
    from sheeprl_tpu.obs import RecompileMonitor
    from sheeprl_tpu.obs.telemetry import device_memory_stats, host_rss_mb

    monitor = RecompileMonitor(name=f"bench:{section}", warn=False).install()
    metric = {
        "dv3": bench_dv3,
        "loop": bench_loop,
        "jaxenv": bench_jaxenv,
        "replay": bench_replay,
        "ckpt": bench_ckpt,
        "serve": bench_serve,
        "ppo": bench_ppo,
        "sac": bench_sac,
        "a2c": bench_a2c,
        "dec": bench_dec,
        "fanin": bench_fanin,
        "transport": bench_transport,
        "wire": bench_wire,
        "mesh": bench_mesh,
        "superbench": bench_superbench,
    }[section]()
    with open(out_path, "w") as f:
        json.dump(metric, f)
    _note(
        event="telemetry",
        section=section,
        compiles=monitor.snapshot(),
        hbm=device_memory_stats(),
        host_rss_mb=host_rss_mb(),
    )


def main():
    # Parent: never imports jax.  Emits ONLY metric JSON lines on stdout,
    # each exactly once (dv3 deferred so it closes the stream).
    metrics = {}
    emitted = set()
    child = {"proc": None, "section": None}

    def _emit(section):
        if section in metrics and section not in emitted:
            sys.stdout.write(json.dumps(metrics[section]) + "\n")
            sys.stdout.flush()
            emitted.add(section)
    # fresh event log per run (it is machine-local and git-ignored)
    try:
        os.makedirs(os.path.dirname(RESULTS_PATH), exist_ok=True)
        open(RESULTS_PATH, "w").close()
    except OSError:
        pass

    def _harvest(section):
        # a killed child may still have finished its measurement: the metric
        # is written to out_path before interpreter teardown starts
        try:
            with open(f"/tmp/sheeprl_bench_{section}.json") as f:
                metrics[section] = json.load(f)
                return True
        except (OSError, ValueError):
            return False

    def _on_term(signum, frame):
        # driver timeout: kill the running section, flush anything not yet
        # on stdout (the deferred dv3 line + a harvested partial section)
        if child["proc"] is not None and child["proc"].poll() is None:
            child["proc"].kill()
        if child["section"] is not None and child["section"] not in metrics:
            _harvest(child["section"])
        for key in [s for s, _ in SECTIONS if s != "dv3"] + ["dv3"]:
            _emit(key)
        _note(event="sigterm", emitted=list(metrics))
        os._exit(1)

    signal.signal(signal.SIGTERM, _on_term)
    section_wall_s = {}
    _note(event="start", budget_s=BUDGET_S)
    for section, est_s in SECTIONS:
        if os.environ.get(f"BENCH_SKIP_{section.upper()}"):
            _note(event="skip", section=section, reason="env")
            continue
        remaining = BUDGET_S - (time.perf_counter() - T_START)
        if remaining < est_s:
            _note(event="skip", section=section, reason="budget", remaining_s=round(remaining, 1))
            continue
        out_path = f"/tmp/sheeprl_bench_{section}.json"
        try:
            os.unlink(out_path)
        except FileNotFoundError:
            pass
        t0 = time.perf_counter()
        try:
            with open(LOG_PATH, "a") as log_f:
                child["section"] = section
                child["proc"] = subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__), "--section", section, out_path],
                    stdout=log_f,
                    stderr=log_f,
                    cwd=REPO,
                )
                try:
                    child["proc"].wait(timeout=max(remaining - 2, 5))
                except subprocess.TimeoutExpired:
                    child["proc"].kill()
                    child["proc"].wait()
                    raise
                finally:
                    child["proc"] = None
                    child["section"] = None
            with open(out_path) as f:
                metric = json.load(f)
            metrics[section] = metric
            if section != "dv3":  # dv3 is deferred to close the stream
                _emit(section)
            section_wall_s[section] = round(time.perf_counter() - t0, 1)
            _note(event="done", section=section, section_s=section_wall_s[section], **metric)
        except subprocess.TimeoutExpired:
            # the measurement may have completed during interpreter teardown
            if _harvest(section):
                if section != "dv3":
                    _emit(section)
                _note(event="timeout_harvested", section=section, **metrics[section])
            else:
                _note(event="timeout", section=section, section_s=round(time.perf_counter() - t0, 1))
        except (OSError, ValueError) as e:
            _note(event="error", section=section, error=f"{type(e).__name__}: {e}")

    # Flush the deferred flagship line LAST — the driver's tail parser
    # reads the last lines, and every section appears exactly once.
    for key in [s for s, _ in SECTIONS if s != "dv3"] + ["dv3"]:
        _emit(key)
    _note(event="end", total_s=round(time.perf_counter() - T_START, 1), emitted=list(metrics))
    # one machine-readable summary of the whole run: per-section
    # wall-seconds (from the per-section done events) + the trace-export
    # path the transport section produced, so a perf investigation can
    # jump from bench_last.jsonl straight into perfetto
    _note(
        event="sections",
        wall_s=dict(section_wall_s),
        trace_export_path=(metrics.get("transport") or {}).get("trace_export_path"),
    )
    # perf-regression gate vs the previous committed BENCH_r*.json: loud
    # failure (stderr + non-zero exit) on >20% regressions of directional
    # headline metrics, skip-list exempt (benchmarks/bench_gate_skiplist.json)
    if metrics and not os.environ.get("BENCH_SKIP_GATE"):
        gate = run_perf_gate(metrics)
        _note(event="gate", **gate)
        if gate["regressions"]:
            sys.stderr.write(
                "PERF REGRESSION GATE FAILED (>"
                f"{gate['threshold_pct']}% vs {gate['baseline_round']}):\n"
                + "".join(
                    f"  {r['metric']}: {r['previous']} -> {r['current']} {r['unit']} "
                    f"({r['regression_pct']:+.1f}%)\n"
                    for r in gate["regressions"]
                )
            )
            sys.stderr.flush()
            sys.exit(3)
    # trend epilogue (ISSUE 16): cross-round headline table on STDERR
    # (stdout is reserved for metric lines) — pure-stdlib script, shelled
    # out so a bug in it can never corrupt the metric stream
    try:
        trend = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "bench_trend.py")],
            capture_output=True,
            text=True,
            timeout=30,
        )
        if trend.returncode == 0 and trend.stdout:
            sys.stderr.write("\n" + trend.stdout)
            sys.stderr.flush()
    except (OSError, subprocess.SubprocessError):
        pass


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--section":
        child_main(sys.argv[2], sys.argv[3])
    else:
        main()
