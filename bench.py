"""Benchmark harness — prints one JSON line per metric for the driver.

Line 1 — PPO wall-clock, the reference's own benchmark protocol
(reference benchmarks/benchmark.py + configs/exp/ppo_benchmarks.yaml):
PPO on CartPole-v1, 1 env, 65536 total steps, linear actor/critic heads,
logging/checkpoint/test disabled, wall-clock around cli.run().
Baseline: 81.27 s (reference README.md:100-115, SheepRL v0.5.5, 1 device).

Line 2 — the north star (BASELINE.md): DreamerV3-S replayed-frames/s of
the full jitted train step on Atari-shaped pixels (B=16, T=64, 64x64x3).
Baseline: the reference's Atari-100K MsPacman run (README.md:44-51) —
100K policy steps x replay_ratio 1 = 100K gradient steps x 1024 frames
in 14 h on an RTX 3080 ~= 2032 replayed frames/s.

``vs_baseline`` is the speedup factor (>1 is faster than the reference).

Line 3 — SAC wall-clock, the reference's benchmark protocol
(configs/exp/sac_benchmarks.yaml: LunarLanderContinuous, 65536 steps,
1 gradient step per env step). ``algo.dispatch_batch=64`` batches 64
gradient steps into one jitted dispatch — same total work, amortized
device-dispatch latency. Baseline: 320.21 s (reference README.md:133-149).

Env overrides:
  BENCH_TOTAL_STEPS  — shrink the PPO workload (wall-clock is extrapolated
                       linearly to 65536 for the reported value).
  BENCH_DV3_STEPS    — timed DV3 train steps (default 20).
  BENCH_SAC_STEPS    — shrink the SAC workload (linear extrapolation).
  BENCH_SKIP_DV3 / BENCH_SKIP_PPO / BENCH_SKIP_SAC — skip a section.
"""

import json
import os
import sys
import time

REFERENCE_PPO_SECONDS = 81.27
REFERENCE_SAC_SECONDS = 320.21
REFERENCE_DV3_FRAMES_PER_S = 2032.0
FULL_STEPS = 65536


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    total_steps = int(os.environ.get("BENCH_TOTAL_STEPS", FULL_STEPS))

    # the axon sitecustomize pins jax to the TPU tunnel; BENCH_PLATFORM=cpu
    # lets the benchmark run on the host backend for local testing
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    else:
        # make the host CPU backend available alongside the TPU so the
        # env-interaction player can run host-side (see MeshRuntime.player_device)
        try:
            current = jax.config.jax_platforms or "axon"
            if "cpu" not in current:
                jax.config.update("jax_platforms", f"{current},cpu")
        except Exception:
            pass

    if not os.environ.get("BENCH_SKIP_PPO"):
        from sheeprl_tpu.cli import run

        args = [
            "exp=ppo_benchmarks",
            f"algo.total_steps={total_steps}",
        ]
        tic = time.perf_counter()
        run(args)
        elapsed = time.perf_counter() - tic
        scaled = elapsed * (FULL_STEPS / total_steps)
        result = {
            "metric": "ppo_cartpole_benchmark_wallclock",
            "value": round(scaled, 2),
            "unit": "s",
            "vs_baseline": round(REFERENCE_PPO_SECONDS / scaled, 3),
        }
        print(json.dumps(result))

    if not os.environ.get("BENCH_SKIP_SAC"):
        from sheeprl_tpu.cli import run

        sac_steps = int(os.environ.get("BENCH_SAC_STEPS", FULL_STEPS))
        tic = time.perf_counter()
        run(
            [
                "exp=sac_benchmarks",
                f"algo.total_steps={sac_steps}",
                "algo.dispatch_batch=64",
                "root_dir=/tmp/sheeprl_tpu_bench_sac",
            ]
        )
        sac_scaled = (time.perf_counter() - tic) * (FULL_STEPS / sac_steps)
        print(
            json.dumps(
                {
                    "metric": "sac_lunarlander_benchmark_wallclock",
                    "value": round(sac_scaled, 2),
                    "unit": "s",
                    "vs_baseline": round(REFERENCE_SAC_SECONDS / sac_scaled, 3),
                }
            )
        )

    if not os.environ.get("BENCH_SKIP_DV3"):
        from benchmarks.bench_dv3_step import time_variant

        dv3_steps = int(os.environ.get("BENCH_DV3_STEPS", 20))
        dt, t_len, b_size = time_variant(fused=False, precision="bf16-mixed", steps=dv3_steps)
        frames_per_s = t_len * b_size / dt
        print(
            json.dumps(
                {
                    "metric": "dreamer_v3_S_train_replayed_frames_per_s",
                    "value": round(frames_per_s, 1),
                    "unit": "frames/s",
                    "vs_baseline": round(frames_per_s / REFERENCE_DV3_FRAMES_PER_S, 3),
                }
            )
        )


if __name__ == "__main__":
    main()
