"""Benchmark harness — prints ONE JSON line for the driver.

Workload: the reference's own PPO benchmark protocol
(reference benchmarks/benchmark.py + configs/exp/ppo_benchmarks.yaml):
PPO on CartPole-v1, 1 env, 65536 total steps, linear actor/critic heads,
logging/checkpoint/test disabled, wall-clock around cli.run().

Baseline: 81.27 s (reference README.md:100-115, SheepRL v0.5.5, 1 device).
``vs_baseline`` is the speedup factor (baseline_time / our_time, >1 is
faster than the reference).

Env overrides:
  BENCH_TOTAL_STEPS  — shrink the workload (wall-clock is extrapolated
                       linearly to 65536 for the reported value).
"""

import json
import os
import sys
import time

REFERENCE_PPO_SECONDS = 81.27
FULL_STEPS = 65536


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    total_steps = int(os.environ.get("BENCH_TOTAL_STEPS", FULL_STEPS))

    # the axon sitecustomize pins jax to the TPU tunnel; BENCH_PLATFORM=cpu
    # lets the benchmark run on the host backend for local testing
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    else:
        # make the host CPU backend available alongside the TPU so the
        # env-interaction player can run host-side (see MeshRuntime.player_device)
        try:
            current = jax.config.jax_platforms or "axon"
            if "cpu" not in current:
                jax.config.update("jax_platforms", f"{current},cpu")
        except Exception:
            pass

    from sheeprl_tpu.cli import run

    args = [
        "exp=ppo_benchmarks",
        f"algo.total_steps={total_steps}",
    ]
    tic = time.perf_counter()
    run(args)
    elapsed = time.perf_counter() - tic
    scaled = elapsed * (FULL_STEPS / total_steps)
    result = {
        "metric": "ppo_cartpole_benchmark_wallclock",
        "value": round(scaled, 2),
        "unit": "s",
        "vs_baseline": round(REFERENCE_PPO_SECONDS / scaled, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
