"""Generalized decoupled topology template — N players / 1 learner.

Counterpart of the reference's examples/architecture_template.py (which
documents an N-player/M-trainer/1-buffer TorchCollective topology). The
TPU-native mapping collapses the M DDP trainer ranks into ONE SPMD learner
process driving the whole device mesh (data parallelism is a mesh axis, the
gradient all-reduce is an XLA collective), while players stay host
processes pinned to the CPU backend and exchange numpy pytrees over
multiprocessing queues — exactly the machinery behind
``sheeprl_tpu/algos/ppo/ppo_decoupled.py`` and ``sac/sac_decoupled.py``.

Topology::

    player-0 ─┐                      ┌─> resp_q[0] ─> player-0
    player-1 ─┼─ data_q ─> LEARNER ──┼─> resp_q[1] ─> player-1
    player-N ─┘   (TPU mesh, 1 jit)  └─> resp_q[N] ─> player-N

Protocol per player (mirrors the reference collective protocol):
  ("init", spaces...)          player -> learner   agent blueprint
  ("params", tree)             learner -> player   initial weights
  ("data", rollout, meta)      player -> learner   experience
  ("update", tree, metrics)    learner -> player   refreshed weights
  ("ckpt_req",)/("ckpt_state") on demand            checkpoint handoff
  ("stop",)                    player -> learner   shutdown sentinel

Run: python examples/architecture_template.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import multiprocessing as mp


def player_loop(player_id: int, cfg: dict, data_q: mp.Queue, resp_q: mp.Queue) -> None:
    """One env-interaction process, pinned to the host CPU backend."""
    import numpy as np

    rng = np.random.default_rng(player_id)
    # 1. handshake: ship the agent blueprint, receive initial weights
    data_q.put(("init", player_id, {"obs_dim": 4, "act_dim": 2}))
    tag, params = resp_q.get()
    assert tag == "params"

    for it in range(cfg["iters"]):
        # 2. collect a (tiny, fake) rollout with the current weights
        rollout = {
            "obs": rng.normal(size=(cfg["rollout"], 4)).astype(np.float32),
            "rew": rng.normal(size=(cfg["rollout"], 1)).astype(np.float32),
        }
        data_q.put(("data", player_id, rollout))
        # 3. refreshed weights back
        tag, params, metrics = resp_q.get()
        assert tag == "update"
    data_q.put(("stop", player_id))


def learner_loop(n_players: int, cfg: dict, data_q: mp.Queue, resp_qs: list) -> None:
    """The single SPMD learner: in a real algorithm this owns the device
    mesh and a jitted update (see MeshRuntime.setup_step)."""
    import numpy as np

    params = {"w": np.zeros((4, 2), np.float32)}
    # one uniform message loop: init handshakes, data and stop sentinels
    # interleave freely across players
    stopped = set()
    step = 0
    while len(stopped) < n_players:
        msg = data_q.get()
        if msg[0] == "init":
            resp_qs[msg[1]].put(("params", params))
        elif msg[0] == "stop":
            stopped.add(msg[1])
        else:
            _, pid, rollout = msg
            # one jitted gradient step over the mesh would go here
            params = {"w": params["w"] + 1e-3 * rollout["obs"].mean()}
            step += 1
            resp_qs[pid].put(("update", params, {"step": step}))
    print(f"learner done after {step} updates")


if __name__ == "__main__":
    N_PLAYERS = 3
    CFG = {"iters": 5, "rollout": 16}
    ctx = mp.get_context("spawn")
    data_q: mp.Queue = ctx.Queue()
    resp_qs = [ctx.Queue() for _ in range(N_PLAYERS)]
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    procs = [
        ctx.Process(target=player_loop, args=(i, CFG, data_q, resp_qs[i])) for i in range(N_PLAYERS)
    ]
    for p in procs:
        p.start()
    learner_loop(N_PLAYERS, CFG, data_q, resp_qs)
    for p in procs:
        p.join()
    print("ok")
