"""Print the observation/action space an algorithm will see for a given
config (counterpart of the reference's examples/observation_space.py).

Usage:
    python examples/observation_space.py exp=ppo env.id=CartPole-v1
    python examples/observation_space.py exp=dreamer_v3 env=atari
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sheeprl_tpu.config import compose
from sheeprl_tpu.utils.env import make_env

if __name__ == "__main__":
    cfg = compose(overrides=list(sys.argv[1:]) or ["exp=ppo", "env.id=CartPole-v1"])
    cfg.env.capture_video = False
    env = make_env(cfg, cfg.seed, 0, None, "example")()
    print(f"env id:             {cfg.env.id}")
    print(f"observation space:  {env.observation_space}")
    print(f"action space:       {env.action_space}")
    print(f"cnn encoder keys:   {cfg.algo.cnn_keys.encoder}")
    print(f"mlp encoder keys:   {cfg.algo.mlp_keys.encoder}")
    obs, _ = env.reset(seed=cfg.seed)
    print("sample obs shapes: ", {k: v.shape for k, v in obs.items()})
    env.close()
