"""Demonstrate the replay-ratio scheduler (counterpart of the reference's
examples/ratio.py): the ``Ratio`` accumulates gradient-step credit at
``replay_ratio`` per policy step and pays it out in integer repeats.

Run: python examples/ratio.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from sheeprl_tpu.utils.utils import Ratio

if __name__ == "__main__":
    num_envs = 1
    world_size = 1
    replay_ratio = 0.0625
    total_policy_steps = 2**10
    learning_starts = 128

    r = Ratio(ratio=replay_ratio, pretrain_steps=0)
    policy_steps_per_iter = num_envs * world_size
    gradient_steps = 0
    for i in range(0, total_policy_steps, policy_steps_per_iter):
        if i >= learning_starts:
            gradient_steps += r(i / world_size)
    print(f"replay ratio (cfg):      {replay_ratio}")
    print(f"gradient steps:          {gradient_steps}")
    print(f"policy steps:            {total_policy_steps}")
    print(f"measured ratio:          {gradient_steps / total_policy_steps:.4f}")
