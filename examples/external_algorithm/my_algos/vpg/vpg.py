"""Vanilla policy gradient (REINFORCE with a learned value baseline) as an
EXTERNAL algorithm: this file lives outside the sheeprl_tpu package and
registers itself through the public registry.

The walkthrough in howto/register_external_algorithm.md builds this file
up section by section.  TPU-first structure (the same rules the built-in
algorithms follow):

- ONE jitted update per iteration; the returns-to-go recursion is a
  reversed ``lax.scan``, not a Python loop;
- the update takes and returns ALL mutable state (params, opt state);
- env interaction stays host-side, with the policy pinned via
  ``runtime.player_device`` so tunneled chips don't eat a round-trip per
  env step;
- no minibatch shuffling, so the update needs no ``shard_map``: with the
  rollout sharded over the mesh's env axis GSPMD parallelizes the global
  mean losses correctly on its own (contrast ppo.py, whose epoch shuffle
  is exactly what forces its explicit DDP core).
"""

from __future__ import annotations

import os
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from my_algos.vpg.agent import build_agent, prepare_obs, VPGPlayer
from my_algos.vpg.utils import test
from sheeprl_tpu.algos.ppo.ppo import build_ppo_optimizer
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.optim import restore_opt_states
from sheeprl_tpu.utils.callback import CheckpointCallback, load_checkpoint
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator, SumMetric
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import device_get_metrics, save_configs


def make_update_fn(runtime, module, tx, cfg: Dict[str, Any]):
    gamma = float(cfg.algo.gamma)
    vf_coef = float(cfg.algo.vf_coef)
    ent_coef = float(cfg.algo.ent_coef)

    def update(params, opt_state, obs, actions, rewards, dones, next_obs):
        """obs (T, N, D), actions (T, N), rewards/dones (T, N, 1)."""

        def loss_fn(p):
            logits, values = module.apply(p, obs)  # (T, N, A), (T, N)
            _, next_value = module.apply(p, next_obs)  # bootstrap (N,)

            def ret_step(carry, inp):
                r, d = inp
                g = r + gamma * carry * (1.0 - d)
                return g, g

            _, returns = jax.lax.scan(
                ret_step,
                next_value,
                (rewards[..., 0], dones[..., 0]),
                reverse=True,
            )  # (T, N)
            adv = returns - jax.lax.stop_gradient(values)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, actions[..., None].astype(jnp.int32), -1)[..., 0]
            pg_loss = -(logp * jax.lax.stop_gradient(adv)).mean()
            v_loss = 0.5 * jnp.square(values - jax.lax.stop_gradient(returns)).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg_loss + vf_coef * v_loss - ent_coef * entropy
            return total, (pg_loss, v_loss)

        (_, (pg_loss, v_loss)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"Loss/policy_loss": pg_loss, "Loss/value_loss": v_loss}

    # setup_step jits under the mesh and donates the old params/opt buffers
    return runtime.setup_step(update, donate_argnums=(0, 1))


@register_algorithm()
def main(runtime, cfg: Dict[str, Any]):
    if len(cfg.algo.cnn_keys.encoder) > 0:
        raise ValueError("vpg supports only vector observations (mlp keys)")
    world_size = runtime.world_size
    runtime.seed_everything(cfg.seed)

    state = load_checkpoint(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None

    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    runtime.print(f"Log dir: {log_dir}")
    if logger:
        logger.log_hyperparams(cfg)

    from gymnasium.vector import AutoresetMode, SyncVectorEnv

    total_envs = cfg.env.num_envs * world_size
    envs = SyncVectorEnv(
        [
            make_env(cfg, cfg.seed + i, 0, log_dir if runtime.is_global_zero else None,
                     "train", vector_env_idx=i)
            for i in range(total_envs)
        ],
        autoreset_mode=AutoresetMode.SAME_STEP,
    )
    if not isinstance(envs.single_action_space, gym.spaces.Discrete):
        raise ValueError("vpg needs a single Discrete action space")
    obs_keys = list(cfg.algo.mlp_keys.encoder)
    actions_dim = (int(envs.single_action_space.n),)

    module, params = build_agent(
        runtime, actions_dim, False, cfg, envs.single_observation_space,
        state["agent"] if state else None,
    )
    params = runtime.replicate(runtime.to_param_dtype(params))
    # the shared optimizer factory honors EVERY key the composed /optim
    # group sets (eps, betas, weight_decay) plus precision master weights —
    # optax.adam(lr) alone would silently drop them
    tx = build_ppo_optimizer(cfg.algo.optimizer, 0.0, runtime.precision)
    opt_state = (
        runtime.replicate(tx.init(params))
        if state is None
        else restore_opt_states(state["optimizer"], params, runtime.precision)
    )
    update_fn = make_update_fn(runtime, module, tx, cfg)
    player = VPGPlayer(module, params, obs_keys, total_envs,
                       device=runtime.player_device(params))

    if runtime.is_global_zero:
        save_configs(cfg, log_dir)
    aggregator = None
    if not MetricAggregator.disabled:
        aggregator = instantiate(dict(cfg.metric.aggregator))

    rb = ReplayBuffer(
        cfg.algo.rollout_steps,
        total_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{runtime.global_rank}"),
        obs_keys=obs_keys,
    )
    ckpt_cb = CheckpointCallback(keep_last=cfg.checkpoint.keep_last)

    start_iter = (state["iter_num"] // world_size) + 1 if state else 1
    policy_step = state["iter_num"] * cfg.env.num_envs * cfg.algo.rollout_steps if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(cfg.env.num_envs * cfg.algo.rollout_steps * world_size)
    total_iters = cfg.algo.total_steps // policy_steps_per_iter if not cfg.dry_run else 1

    step_data: Dict[str, np.ndarray] = {}
    next_obs_np = envs.reset(seed=cfg.seed)[0]
    for iter_num in range(start_iter, total_iters + 1):
        with timer("Time/env_interaction_time", SumMetric, sync_on_compute=False):
            for _ in range(cfg.algo.rollout_steps):
                policy_step += cfg.env.num_envs * world_size
                actions, _, _ = player.get_actions(next_obs_np, runtime.next_key())
                actions = np.asarray(actions)
                obs, rewards, terminated, truncated, info = envs.step(actions)
                rewards = rewards.astype(np.float32)
                # time-limit truncation is NOT termination: bootstrap the
                # cut episode's tail with gamma * V(final_obs) so the
                # returns/value targets stay unbiased (same treatment as
                # the built-in PPO/A2C)
                truncated_envs = np.nonzero(truncated)[0]
                if len(truncated_envs) > 0:
                    real_next_obs = {k: np.array(v) for k, v in obs.items()}
                    for env_idx in truncated_envs:
                        final = info["final_obs"][env_idx]
                        for k in obs_keys:
                            real_next_obs[k][env_idx] = final[k]
                    vals = np.asarray(player.get_values(real_next_obs))
                    rewards[truncated_envs] += cfg.algo.gamma * vals[truncated_envs]
                dones = np.logical_or(terminated, truncated)
                for k in obs_keys:
                    step_data[k] = next_obs_np[k][np.newaxis]
                step_data["actions"] = actions.reshape(1, total_envs, 1).astype(np.float32)
                step_data["rewards"] = rewards.reshape(1, total_envs, 1).astype(np.float32)
                step_data["dones"] = dones.reshape(1, total_envs, 1).astype(np.float32)
                rb.add(step_data, validate_args=cfg.buffer.validate_args)
                next_obs_np = obs

                if cfg.metric.log_level > 0 and "final_info" in info:
                    ep = info["final_info"].get("episode")
                    if ep is not None:
                        for i in np.nonzero(info["final_info"]["_episode"])[0]:
                            if aggregator and "Rewards/rew_avg" in aggregator:
                                aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                            if aggregator and "Game/ep_len_avg" in aggregator:
                                aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                            runtime.print(
                                f"Rank-0: policy_step={policy_step}, reward_env_{i}={float(ep['r'][i])}"
                            )

        data = rb.to_arrays()
        # env-axis sharding: each mesh device gets its own env columns
        obs_dev = runtime.shard_batch(
            jnp.concatenate(
                [jnp.asarray(data[k], jnp.float32).reshape(*data[k].shape[:2], -1) for k in obs_keys],
                axis=-1,
            ),
            axis=1,
        )
        next_obs_dev = runtime.shard_batch(prepare_obs(next_obs_np, obs_keys, total_envs), axis=0)
        with timer("Time/train_time", SumMetric, sync_on_compute=cfg.metric.sync_on_compute):
            params, opt_state, train_metrics = update_fn(
                params, opt_state, obs_dev,
                runtime.shard_batch(jnp.asarray(data["actions"][..., 0]), axis=1),
                runtime.shard_batch(jnp.asarray(data["rewards"]), axis=1),
                runtime.shard_batch(jnp.asarray(data["dones"]), axis=1),
                next_obs_dev,
            )
        player.params = params

        if aggregator and not aggregator.disabled:
            for k, v in device_get_metrics(train_metrics).items():
                aggregator.update(k, v)
        if cfg.metric.log_level > 0 and logger and (
            policy_step - last_log >= cfg.metric.log_every or iter_num == total_iters
        ):
            if aggregator and not aggregator.disabled:
                logger.log_metrics(aggregator.compute(), policy_step)
                aggregator.reset()
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time", 0) > 0:
                    logger.log_metrics(
                        {"Time/sps_train": (iter_num - start_iter + 1) / timer_metrics["Time/train_time"]},
                        policy_step,
                    )
                if timer_metrics.get("Time/env_interaction_time", 0) > 0:
                    logger.log_metrics(
                        {
                            "Time/sps_env_interaction": (policy_step - last_log)
                            / timer_metrics["Time/env_interaction_time"]
                        },
                        policy_step,
                    )
                timer.reset()
            last_log = policy_step

        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            iter_num == total_iters and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            ckpt_cb.save(
                runtime,
                os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{runtime.global_rank}.ckpt"),
                {
                    "agent": params,
                    "optimizer": opt_state,
                    "iter_num": iter_num * world_size,
                    "batch_size": cfg.algo.rollout_steps * world_size,
                    "last_log": last_log,
                    "last_checkpoint": last_checkpoint,
                },
            )

    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        test(player, runtime, cfg, log_dir)
    if logger:
        logger.finalize()
