"""Evaluation entrypoint: ``python sheeprl_eval.py checkpoint_path=...``
resolves ``cfg.algo.name`` through the registry and imports
``<root_module>.evaluate`` — for an external package that is this file."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym

from my_algos.vpg.agent import build_agent, VPGPlayer
from my_algos.vpg.utils import test
from sheeprl_tpu.utils.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms="vpg")
def evaluate_vpg(runtime, cfg: Dict[str, Any], state: Dict[str, Any]):
    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    runtime.print(f"Log dir: {log_dir}")
    runtime.seed_everything(cfg.seed)

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    if not isinstance(env.action_space, gym.spaces.Discrete):
        raise RuntimeError("vpg evaluates single Discrete action spaces only")
    actions_dim = (int(env.action_space.n),)
    obs_space = env.observation_space
    env.close()

    module, params = build_agent(runtime, actions_dim, False, cfg, obs_space, state["agent"])
    player = VPGPlayer(module, params, list(cfg.algo.mlp_keys.encoder), num_envs=1)
    rew = test(player, runtime, cfg, log_dir)
    if logger:
        logger.log_metrics({"Test/cumulative_reward": rew}, 0)
        logger.finalize()
