"""Metric whitelist + model-manager keys + greedy test rollout for vpg."""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from my_algos.vpg.agent import VPGPlayer
from sheeprl_tpu.utils.env import make_env

# metrics the aggregator is allowed to track (see howto/logs_and_checkpoints.md)
AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/policy_loss",
    "Loss/value_loss",
}
# checkpoint keys the model manager can publish (see howto/model_manager.md)
MODELS_TO_REGISTER = {"agent"}


def test(player: VPGPlayer, runtime, cfg: Dict[str, Any], log_dir: str) -> float:
    """Greedy rollout of one episode on rank 0."""
    single = VPGPlayer(player.module, player.params, player.mlp_keys, num_envs=1)
    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    obs = env.reset(seed=cfg.seed)[0]
    done, cumulative_rew = False, 0.0
    while not done:
        actions, _, _ = single.get_actions(obs, runtime.next_key(), greedy=True)
        obs, reward, terminated, truncated, _ = env.step(int(np.asarray(actions)[0]))
        done = bool(terminated or truncated)
        cumulative_rew += float(reward)
        if cfg.dry_run:
            done = True
    runtime.print("Test - Reward:", cumulative_rew)
    env.close()
    return cumulative_rew
