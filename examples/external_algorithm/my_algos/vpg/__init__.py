"""Importing this package registers the algorithm + its evaluation
(registration is an import side-effect, exactly like the built-ins in
``sheeprl_tpu/algos/__init__.py``)."""

from my_algos.vpg import evaluate, vpg  # noqa: F401
