"""VPG agent: one flax module (shared torso, policy + value heads) and a
host-side player for the env hot loop.

The framework's contract (howto/register_new_algorithm.md): "the agent" is
a pair ``(module, params)`` — the module holds architecture, the param
pytree holds the numbers, and nothing is ever mutated in place."""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models.models import MLP
from sheeprl_tpu.utils.utils import transfer_tree


class VPGAgentModule(nn.Module):
    n_actions: int
    dense_units: int = 64
    mlp_layers: int = 2

    @nn.compact
    def __call__(self, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """obs (..., D) -> (logits (..., A), value (...,))."""
        h = MLP(hidden_sizes=(self.dense_units,) * self.mlp_layers)(obs)
        logits = nn.Dense(self.n_actions)(h)
        value = nn.Dense(1)(h)[..., 0]
        return logits, value


def prepare_obs(obs: Dict[str, Any], mlp_keys: Sequence[str], num_envs: int) -> jax.Array:
    """Concat the requested vector keys into a flat (num_envs, D) batch."""
    return jnp.concatenate(
        [jnp.asarray(obs[k], jnp.float32).reshape(num_envs, -1) for k in mlp_keys], axis=-1
    )


class VPGPlayer:
    """Env-loop policy wrapper: jitted sample/greedy action selection bound
    to a mutable params reference.  ``device`` comes from
    ``runtime.player_device(params)`` — on tunneled-TPU machines a tiny
    policy runs on the host CPU backend so each env step skips the link
    round-trip (see howto/scaling.md)."""

    def __init__(self, module: VPGAgentModule, params: Any, mlp_keys: Sequence[str],
                 num_envs: int, device=None):
        self.module = module
        self.mlp_keys = list(mlp_keys)
        self.num_envs = num_envs
        self.device = device
        self._params = jax.device_put(params, device) if device is not None else params

        def _act(p, obs, key, greedy):
            logits, value = module.apply(p, obs)
            actions = jnp.where(
                greedy, jnp.argmax(logits, -1), jax.random.categorical(key, logits)
            )
            logp = jnp.take_along_axis(jax.nn.log_softmax(logits), actions[:, None], 1)[:, 0]
            return actions, logp, value

        self._act = jax.jit(_act)
        self._values = jax.jit(lambda p, obs: module.apply(p, obs)[1])

    @property
    def params(self) -> Any:
        return self._params

    @params.setter
    def params(self, value: Any) -> None:
        # mesh-placed arrays cannot enter another backend's jit directly;
        # transfer_tree batches the whole pytree into ONE cross-backend
        # copy (leaf-by-leaf device_put pays the link latency per leaf —
        # see howto/scaling.md "player placement")
        self._params = transfer_tree(value, self.device)

    def get_actions(self, obs: Dict[str, Any], key: jax.Array, greedy: bool = False):
        prepared = prepare_obs(obs, self.mlp_keys, self.num_envs)
        if self.device is not None:
            prepared = jax.device_put(prepared, self.device)
            key = jax.device_put(key, self.device)
        return self._act(self._params, prepared, key, greedy)

    def get_values(self, obs: Dict[str, Any]) -> jax.Array:
        prepared = prepare_obs(obs, self.mlp_keys, self.num_envs)
        if self.device is not None:
            prepared = jax.device_put(prepared, self.device)
        return self._values(self._params, prepared)


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space,
    agent_state: Optional[Any] = None,
) -> Tuple[VPGAgentModule, Any]:
    if is_continuous or len(actions_dim) != 1:
        raise ValueError("vpg is a single-discrete-action-space tutorial algorithm")
    module = VPGAgentModule(
        n_actions=int(actions_dim[0]),
        dense_units=int(cfg.algo.dense_units),
        mlp_layers=int(cfg.algo.mlp_layers),
    )
    obs_dim = sum(int(np.prod(obs_space[k].shape)) for k in cfg.algo.mlp_keys.encoder)
    # init from the SEEDED runtime key (the same contract as the built-ins,
    # ppo/agent.py:280) so different seeds start from different weights; a
    # checkpoint, when given, overwrites the values right after
    params = module.init(runtime.next_key(), jnp.zeros((1, obs_dim)))
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    return module, params
