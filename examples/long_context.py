"""Long-context sequence parallelism demo.

Trains a causal SequenceTransformer on a copy task with the SEQUENCE axis
sharded over an 8-device mesh: each device holds S/8 of every sequence,
ring attention rotates K/V shards over the ring (ICI on real hardware)
while an online softmax folds one block per hop, and gradients are
pmean-reduced. Per-device memory stays O(S/8) — the mechanism that scales
to million-token contexts on TPU pods.

Run (no TPU needed):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/long_context.py
"""

import os
import sys

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.models.models import SequenceTransformer
from sheeprl_tpu.parallel import MeshRuntime
from sheeprl_tpu.parallel.sequence import make_sequence_parallel_train_step

if __name__ == "__main__":
    runtime = MeshRuntime(devices=8, strategy="dp", accelerator="cpu").launch()
    vocab, batch, seq = 32, 4, 128  # sequence sharded 16 tokens/device

    model = SequenceTransformer(
        vocab_size=vocab, embed_dim=64, depth=2, num_heads=4, max_len=seq,
        parallelism="ring", axis_name="data",
    )
    init_model = SequenceTransformer(  # same params, init outside shard_map
        vocab_size=vocab, embed_dim=64, depth=2, num_heads=4, max_len=seq,
        parallelism="blockwise",
    )

    rng = np.random.default_rng(0)
    half = seq // 2 + 1
    first = rng.integers(1, vocab, (batch, half))
    tokens = np.concatenate([first, first], axis=1)[:, : seq + 1].astype(np.int32)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]

    params = init_model.init(jax.random.PRNGKey(0), jnp.asarray(inputs[:, : seq // 8]))
    tx = optax.adam(3e-3)
    step, token_sharding = make_sequence_parallel_train_step(runtime.mesh, model, tx)

    params = runtime.replicate(params)
    opt_state = runtime.replicate(tx.init(params))
    inputs = jax.device_put(jnp.asarray(inputs), token_sharding)
    targets = jax.device_put(jnp.asarray(targets), token_sharding)

    n_iters = int(os.environ.get("LONG_CONTEXT_ITERS", 30))
    for it in range(n_iters):
        params, opt_state, loss = step(params, opt_state, inputs, targets)
        if it % 10 == 0:
            print(f"iter {it:3d}  loss {float(loss):.4f}")
    print(f"final loss {float(loss):.4f} (copy task; random = {np.log(vocab):.2f})")
