from sheeprl_tpu.cli import registration

if __name__ == "__main__":
    registration()
